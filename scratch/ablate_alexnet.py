import json, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.flops import mfu, net_train_flops
from singa_tpu.utils.profiler import hard_sync

BS, ITERS = 2048, 20

def rewire(layers, removed):
    """Drop layers named in `removed`, rewiring consumers to their src."""
    alias = {}
    out = []
    for l in layers:
        src = l.get("srclayers")
        if isinstance(src, str): src = [src]
        if src: l["srclayers"] = [alias.get(s, s) for s in src]
        if l["name"] in removed:
            alias[l["name"]] = l["srclayers"][0]
            # propagate chained aliases
            alias[l["name"]] = alias.get(alias[l["name"]], alias[l["name"]])
        else:
            out.append(l)
    return out

def build(mod):
    import singa_tpu.models.vision as V
    from singa_tpu.config.schema import model_config_from_dict
    cfg = alexnet_cifar10_full(batchsize=BS)
    d = None
    # easier: rebuild from the builder fns by patching layer dicts
    layers = []
    # reconstruct dict list via the module's private builders
    h = V._data_head(BS, "kRGBImage", rgb_scale=1/255.0)
    layers, head = h
    body = [
        V._conv("conv1", head, 64, 5, 1, 2, std=1e-2),
        V._relu("relu1", "conv1"),
        V._lrn("norm1", "relu1", 5, 1e-4),
        V._pool("pool1", "norm1", 3, 2, "AVE" if mod=="avgpool" else "MAX"),
        V._conv("conv2", "pool1", 192, 5, 1, 2, std=1e-2, bias_value=1.0),
        V._relu("relu2", "conv2"),
        V._lrn("norm2", "relu2", 5, 1e-4),
        V._pool("pool2", "norm2", 3, 2, "AVE" if mod=="avgpool" else "MAX"),
        V._conv("conv3", "pool2", 384, 3, 1, 1, std=1e-2),
        V._relu("relu3", "conv3"),
        V._conv("conv4", "relu3", 256, 3, 1, 1, std=1e-2, bias_value=1.0),
        V._relu("relu4", "conv4"),
        V._conv("conv5", "relu4", 256, 3, 1, 1, std=1e-2, bias_value=1.0),
        V._relu("relu5", "conv5"),
        V._pool("pool5", "relu5", 3, 2, "AVE" if mod=="avgpool" else "MAX"),
        V._ip("fc6", "pool5", 4096, std=5e-3, bias_value=1.0),
        V._relu("relu6", "fc6"),
        V._dropout("drop6", "relu6"),
        V._ip("fc7", "drop6", 4096, std=5e-3, bias_value=1.0),
        V._relu("relu7", "fc7"),
        V._dropout("drop7", "relu7"),
        V._ip("fc8", "drop7", 10, std=1e-2),
        V._loss("fc8"),
    ]
    layers += body
    removed = set()
    if mod == "nolrn": removed = {"norm1", "norm2"}
    elif mod == "nodrop": removed = {"drop6", "drop7"}
    elif mod == "norelu": removed = {f"relu{i}" for i in range(1,8)}
    elif mod == "nolrn_nodrop": removed = {"norm1","norm2","drop6","drop7"}
    layers = rewire(layers, removed)
    return model_config_from_dict({
        "name": f"alexnet-abl-{mod}", "train_steps": 100,
        "display_frequency": 100,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "momentum": 0.9, "weight_decay": 0.0005,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers},
    })

def timeit(cfg, fwd_only=False):
    cfg.precision = "bfloat16"
    shapes = {"data": {"pixel": (3, 32, 32), "label": ()}}
    tr = Trainer(cfg, shapes, log_fn=lambda s: None)
    params, opt_state = tr.init(seed=0)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
        "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
    key = jax.random.PRNGKey(0)
    if fwd_only:
        import functools
        f = jax.jit(lambda p, b, k: tr.train_net.apply(p, b, rng=k, train=True)[0])
        out = f(params, batch, key); hard_sync(out)
        t0 = time.perf_counter()
        for _ in range(ITERS): out = f(params, batch, key)
        hard_sync(out)
        return (time.perf_counter()-t0)/ITERS, tr
    params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, ITERS)
    hard_sync(params)
    t0 = time.perf_counter()
    params, opt_state, _ = tr.train_steps(params, opt_state, batch, ITERS, key, ITERS)
    hard_sync(params)
    return (time.perf_counter()-t0)/ITERS, tr

base_flops = None
for mod in ["base", "fwdonly", "nolrn", "avgpool", "nodrop", "nolrn_nodrop"]:
    cfg = build("base" if mod in ("base","fwdonly") else mod)
    s, tr = timeit(cfg, fwd_only=(mod=="fwdonly"))
    fl = net_train_flops(tr.train_net)
    print(json.dumps({"mod": mod, "step_ms": round(s*1e3,3),
                      "mfu_vs_full": round(mfu(3.1211e12, s) or 0, 4)}))
