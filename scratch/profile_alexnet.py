import sys, glob, json, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.profiler import hard_sync

BS = 2048
cfg = alexnet_cifar10_full(batchsize=BS)
cfg.precision = "bfloat16"
tr = Trainer(cfg, {"data": {"pixel": (3,32,32), "label": ()}}, log_fn=lambda s: None)
params, opt_state = tr.init(seed=0)
rng = np.random.default_rng(0)
batch = {"data": {
    "pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
    "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
key = jax.random.PRNGKey(0)
params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, 5)
hard_sync(params)
logdir = "/root/repo/scratch/trace"
with jax.profiler.trace(logdir):
    params, opt_state, _ = tr.train_steps(params, opt_state, batch, 5, key, 5)
    hard_sync(params)
print("trace done")
