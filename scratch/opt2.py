import json, sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.flops import mfu
from singa_tpu.utils.profiler import hard_sync
import singa_tpu.ops as ops
import singa_tpu.ops.pool as pool_mod
import singa_tpu.core.layers as L

BS, ITERS = 2048, 20
MODEL_TFLOPS = 3.1211e12

# ---- LRN custom_vjp, minimal residual (save x only), all-bf16 ----
def _band(c, local_size, dtype):
    idx = jnp.arange(c)
    return (jnp.abs(idx[:, None] - idx[None, :]) <= local_size // 2).astype(dtype)

def _norm(x, local_size, alpha, knorm):
    sq = jnp.square(x)
    n = jnp.dot(sq, _band(x.shape[-1], local_size, x.dtype))
    return n * jnp.asarray(alpha/local_size, x.dtype) + jnp.asarray(knorm, x.dtype)

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,2,3,4,5))
def lrn2(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, layout="NCHW"):
    n = _norm(x, local_size, alpha, knorm)
    r = lax.rsqrt(n)
    return x * (r * jnp.sqrt(r))

def _lrn2_fwd(x, local_size, alpha, beta, knorm, layout):
    return lrn2(x, local_size, alpha, beta, knorm, layout), x

def _lrn2_bwd(local_size, alpha, beta, knorm, layout, x, g):
    n = _norm(x, local_size, alpha, knorm)
    r = lax.rsqrt(n)          # n^-1/2
    p = r * jnp.sqrt(r)       # n^-3/4
    t = g * x * (p * r * r)   # g*x*n^-7/4
    s = jnp.dot(t, _band(x.shape[-1], local_size, x.dtype))
    dx = g * p - jnp.asarray(2*beta*alpha/local_size, x.dtype) * x * s
    return (dx,)
lrn2.defvjp(_lrn2_fwd, _lrn2_bwd)

def lrn_dispatch(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, layout="NCHW"):
    import importlib; lm = importlib.import_module('singa_tpu.ops.lrn')
    if layout == "NHWC" and beta == 0.75:
        return lrn2(x, local_size, alpha, beta, knorm, layout)
    return lm.lrn(x, local_size, alpha, beta, knorm, layout)

# ---- max pool custom_vjp: fwd reduce_window, bwd mask+dilated pads ----
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,2,3))
def mp2(x, kernel, stride, layout="NCHW"):
    return pool_mod.max_pool2d.__wrapped__(x, kernel, stride, layout) if hasattr(pool_mod.max_pool2d, "__wrapped__") else _mp_fwd_raw(x, kernel, stride, layout)

def _mp_fwd_raw(x, kernel, stride, layout):
    h, w = pool_mod._spatial(x, layout)
    ph, pw = pool_mod._ceil_pad(h, kernel, stride), pool_mod._ceil_pad(w, kernel, stride)
    dims, strides, pad = pool_mod._window(kernel, stride, ph, pw, layout)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)

def _mp_fwd(x, kernel, stride, layout):
    y = _mp_fwd_raw(x, kernel, stride, layout)
    return y, (x, y)

def _mp_bwd(kernel, stride, layout, res, g):
    x, y = res
    assert layout == "NHWC"
    n, h, w, c = x.shape
    ph, pw = pool_mod._ceil_pad(h, kernel, stride), pool_mod._ceil_pad(w, kernel, stride)
    oh, ow = y.shape[1], y.shape[2]
    neg = jnp.asarray(-jnp.inf, x.dtype) if x.dtype != jnp.bfloat16 else jnp.asarray(float(np.finfo(np.float32).min), x.dtype)
    xp = jnp.pad(x, ((0,0),(0,ph),(0,pw),(0,0)), constant_values=neg)
    dx = None
    for ki in range(kernel):
        for kj in range(kernel):
            sl = lax.slice(xp, (0, ki, kj, 0),
                           (n, ki+(oh-1)*stride+1, kj+(ow-1)*stride+1, c),
                           (1, stride, stride, 1))
            contrib = jnp.where(sl == y, g, jnp.zeros((), g.dtype))
            hi_h = (h + ph) - (ki + (oh-1)*stride + 1)
            hi_w = (w + pw) - (kj + (ow-1)*stride + 1)
            padded = lax.pad(contrib, jnp.zeros((), g.dtype),
                             ((0,0,0), (ki, hi_h, stride-1), (kj, hi_w, stride-1), (0,0,0)))
            dx = padded if dx is None else dx + padded
    return (dx[:, :h, :w, :],)
mp2.defvjp(_mp_fwd, _mp_bwd)

def mp_dispatch(x, kernel, stride, layout="NCHW"):
    if layout == "NHWC":
        return mp2(x, kernel, stride, layout)
    return _mp_fwd_raw(x, kernel, stride, layout)

def timeit(mods):
    import importlib; lm = importlib.import_module('singa_tpu.ops.lrn')
    orig = (ops.lrn, L.ops.lrn, ops.max_pool2d, L.ops.max_pool2d)
    if "lrn" in mods: ops.lrn = L.ops.lrn = lrn_dispatch
    if "pool" in mods: ops.max_pool2d = L.ops.max_pool2d = mp_dispatch
    try:
        cfg = alexnet_cifar10_full(batchsize=BS)
        cfg.precision = "bfloat16"
        tr = Trainer(cfg, {"data": {"pixel": (3,32,32), "label": ()}}, log_fn=lambda s: None)
        tr.train_net.remat_types = set()
        params, opt_state = tr.init(seed=0)
        rng = np.random.default_rng(0)
        batch = {"data": {
            "pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
            "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
        key = jax.random.PRNGKey(0)
        params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, ITERS)
        hard_sync(params)
        t0 = time.perf_counter()
        params, opt_state, _ = tr.train_steps(params, opt_state, batch, ITERS, key, ITERS)
        hard_sync(params)
        return (time.perf_counter()-t0)/ITERS
    finally:
        ops.lrn, L.ops.lrn, ops.max_pool2d, L.ops.max_pool2d = orig

# numeric check of pool bwd vs autodiff oracle
def check():
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (3, 9, 9, 5), jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(2), (3, 4, 4, 5), jnp.float32)
    ref = jax.vjp(lambda z: _mp_fwd_raw(z, 3, 2, "NHWC"), x)[1](g)[0]
    got = jax.vjp(lambda z: mp2(z, 3, 2, "NHWC"), x)[1](g)[0]
    print("pool bwd max diff:", float(jnp.max(jnp.abs(ref-got))))
    # lrn check
    x2 = jax.random.normal(k, (4, 6, 6, 16), jnp.float32)
    g2 = jax.random.normal(jax.random.PRNGKey(3), (4, 6, 6, 16), jnp.float32)
    import importlib; lm = importlib.import_module('singa_tpu.ops.lrn')
    r1 = jax.vjp(lambda z: lm.lrn(z, 5, 1e-4, 0.75, 1.0, "NHWC"), x2)[1](g2)[0]
    r2 = jax.vjp(lambda z: lrn2(z, 5, 1e-4, 0.75, 1.0, "NHWC"), x2)[1](g2)[0]
    print("lrn bwd max diff:", float(jnp.max(jnp.abs(r1-r2))))

check()
for name, mods in [("lrn2", ["lrn"]), ("pool2", ["pool"]), ("both", ["lrn","pool"])]:
    s = timeit(mods)
    print(json.dumps({"variant": name, "step_ms": round(s*1e3,3),
                      "mfu": round(mfu(MODEL_TFLOPS, s) or 0, 4)}))
