"""Minimal xplane.pb reader: aggregate XLA op durations per plane/line."""
import sys, glob, struct, collections

def read_varint(b, i):
    r = 0; s = 0
    while True:
        x = b[i]; i += 1
        r |= (x & 0x7f) << s
        if not x & 0x80: return r, i
        s += 7

def fields(buf):
    i = 0
    while i < len(buf):
        tag, i = read_varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = read_varint(buf, i)
        elif wt == 1:
            v = buf[i:i+8]; i += 8
        elif wt == 2:
            ln, i = read_varint(buf, i)
            v = buf[i:i+ln]; i += ln
        elif wt == 5:
            v = buf[i:i+4]; i += 4
        else:
            raise ValueError(f"wiretype {wt}")
        yield fn, wt, v

def parse(path):
    data = open(path, "rb").read()
    planes = []
    for fn, wt, v in fields(data):
        if fn == 1: planes.append(v)
    out = []
    for p in planes:
        name = ""; lines = []; emeta = {}
        for fn, wt, v in fields(p):
            if fn == 2: name = v.decode()
            elif fn == 3: lines.append(v)
            elif fn == 4:
                k = None; md = None
                for f2, w2, v2 in fields(v):
                    if f2 == 1: k = v2
                    elif f2 == 2: md = v2
                if md is not None:
                    mid = mname = None
                    for f3, w3, v3 in fields(md):
                        if f3 == 1: mid = v3
                        elif f3 == 2: mname = v3.decode()
                    emeta[mid if mid is not None else k] = mname or ""
        out.append((name, lines, emeta))
    return out

def agg(path, plane_filter="TPU"):
    res = {}
    for name, lines, emeta in parse(path):
        if plane_filter not in name: continue
        for ln in lines:
            lname = ""; events = []
            for fn, wt, v in fields(ln):
                if fn == 2: lname = v.decode()
                elif fn == 11: lname = v.decode() or lname
                elif fn == 4: events.append(v)
            d = collections.Counter(); cnt = collections.Counter()
            for ev in events:
                mid = dur = 0
                for fn, wt, v in fields(ev):
                    if fn == 1: mid = v
                    elif fn == 3: dur = v
                opname = emeta.get(mid, str(mid))
                d[opname] += dur; cnt[opname] += 1
            res[(name, lname)] = (d, cnt)
    return res

if __name__ == "__main__":
    path = sorted(glob.glob(sys.argv[1] if len(sys.argv)>1 else
        "/root/repo/scratch/trace/plugins/profile/*/*.xplane.pb"))[-1]
    res = agg(path)
    for (pname, lname), (d, cnt) in res.items():
        tot = sum(d.values())
        if tot == 0: continue
        print(f"=== {pname} / {lname}: total {tot/1e9:.3f} ms")
        for op, ps in d.most_common(40):
            print(f"  {ps/1e9:8.3f} ms  x{cnt[op]:<4} {op[:110]}")
        print()
