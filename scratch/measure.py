import json, sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.flops import mfu, net_train_flops
from singa_tpu.utils.profiler import hard_sync

BS = int(os.environ.get("BS", 2048))
ITERS = int(os.environ.get("ITERS", 20))
REPS = int(os.environ.get("REPS", 6))
cfg = alexnet_cifar10_full(batchsize=BS)
cfg.precision = "bfloat16"
tr = Trainer(cfg, {"data": {"pixel": (3,32,32), "label": ()}}, log_fn=lambda s: None)
params, opt_state = tr.init(seed=0)
rng = np.random.default_rng(0)
batch = {"data": {
    "pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
    "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
key = jax.random.PRNGKey(0)
params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, ITERS)
hard_sync(params)
ts = []
for r in range(REPS):
    t0 = time.perf_counter()
    params, opt_state, _ = tr.train_steps(params, opt_state, batch, ITERS, key, ITERS)
    hard_sync(params)
    ts.append((time.perf_counter()-t0)/ITERS)
fl = net_train_flops(tr.train_net)
best, med = min(ts), sorted(ts)[len(ts)//2]
print(json.dumps({"best_ms": round(best*1e3,3), "med_ms": round(med*1e3,3),
                  "mfu_best": round(mfu(fl, best) or 0, 4),
                  "mfu_med": round(mfu(fl, med) or 0, 4),
                  "all": [round(t*1e3,2) for t in ts]}))
