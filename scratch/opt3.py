import json, sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.flops import mfu
from singa_tpu.utils.profiler import hard_sync
import singa_tpu.ops as ops
import singa_tpu.core.layers as L

BS, ITERS = 2048, 20
MODEL_TFLOPS = 3.1211e12

def _band(c, local_size, dtype):
    idx = jnp.arange(c)
    return (jnp.abs(idx[:, None] - idx[None, :]) <= local_size // 2).astype(dtype)

def make_lrn(window_mode):
    def wsum(t, local_size):
        if window_mode == "dot":
            return jnp.dot(t, _band(t.shape[-1], local_size, t.dtype))
        half = local_size // 2
        c = t.shape[-1]
        tp = jnp.pad(t, [(0,0)]*(t.ndim-1) + [(half, half)])
        out = None
        for d in range(local_size):
            sl = lax.slice_in_dim(tp, d, d + c, axis=-1)
            out = sl if out is None else out + sl
        return out

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,2,3,4,5))
    def lrn_c(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, layout="NCHW"):
        return _fwd(x, local_size, alpha, beta, knorm, layout)[0]
    def _fwd(x, local_size, alpha, beta, knorm, layout):
        sq = jnp.square(x)
        n = wsum(sq, local_size) * jnp.asarray(alpha/local_size, x.dtype) + jnp.asarray(knorm, x.dtype)
        r = lax.rsqrt(n)
        p = r * jnp.sqrt(r)
        return x * p, (x, n, p)
    def _bwd(local_size, alpha, beta, knorm, layout, res, g):
        x, n, p = res
        t = g * x * p / n
        s = wsum(t, local_size)
        dx = g * p - jnp.asarray(2*beta*alpha/local_size, x.dtype) * x * s
        return (dx,)
    lrn_c.defvjp(_fwd, _bwd)
    def dispatch(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, layout="NCHW"):
        import importlib; lm = importlib.import_module('singa_tpu.ops.lrn')
        if layout == "NHWC" and beta == 0.75:
            return lrn_c(x, local_size, alpha, beta, knorm, layout)
        return lm.lrn(x, local_size, alpha, beta, knorm, layout)
    return dispatch

def timeit(lrn_fn):
    orig = (ops.lrn, L.ops.lrn)
    ops.lrn = L.ops.lrn = lrn_fn
    try:
        cfg = alexnet_cifar10_full(batchsize=BS)
        cfg.precision = "bfloat16"
        tr = Trainer(cfg, {"data": {"pixel": (3,32,32), "label": ()}}, log_fn=lambda s: None)
        tr.train_net.remat_types = set()
        params, opt_state = tr.init(seed=0)
        rng = np.random.default_rng(0)
        batch = {"data": {
            "pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
            "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
        key = jax.random.PRNGKey(0)
        params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, ITERS)
        hard_sync(params)
        t0 = time.perf_counter()
        params, opt_state, _ = tr.train_steps(params, opt_state, batch, ITERS, key, ITERS)
        hard_sync(params)
        return (time.perf_counter()-t0)/ITERS
    finally:
        ops.lrn, L.ops.lrn = orig

for name in ["dot", "shift"]:
    s = timeit(make_lrn(name))
    print(json.dumps({"variant": f"lrn_{name}", "step_ms": round(s*1e3,3),
                      "mfu": round(mfu(MODEL_TFLOPS, s) or 0, 4)}))
