import json, sys, time, functools, os
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
import singa_tpu.ops as ops
import singa_tpu.core.layers as L
import importlib
lm = importlib.import_module('singa_tpu.ops.lrn')

# variant: residual x only, recompute s in bwd
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,2,3,4,5))
def lrn_v(x, local_size, alpha, beta, knorm, relu):
    return lm._lrn_nhwc_fwd(x, local_size, alpha, beta, knorm, relu)[0]
def _fwd(x, local_size, alpha, beta, knorm, relu):
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    s = lm._window_sum(a, local_size)
    _, p = lm._p_of_s(s, local_size, alpha, beta, knorm)
    return a * p, x
def _bwd(local_size, alpha, beta, knorm, relu, x, g):
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    s = lm._window_sum(a, local_size)
    n, p = lm._p_of_s(s, local_size, alpha, beta, knorm)
    t = g * a * (p / n)
    u = jnp.dot(t, lm._band(x.shape[-1], local_size, x.dtype))
    da = g * p - jnp.asarray(2*beta*alpha/local_size, x.dtype) * a * u
    if relu:
        da = jnp.where(x > 0, da, jnp.zeros((), da.dtype))
    return (da,)
lrn_v.defvjp(_fwd, _bwd)

def relu_lrn_v(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, relu=False, layout="NHWC"):
    if layout == "NHWC":
        return lrn_v(x, local_size, alpha, beta, knorm, relu)
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    return lm.lrn(a, local_size, alpha, beta, knorm, layout)

ops.relu_lrn = L.ops.relu_lrn = relu_lrn_v

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.flops import mfu, net_train_flops
from singa_tpu.utils.profiler import hard_sync
BS, ITERS = 2048, 20
cfg = alexnet_cifar10_full(batchsize=BS); cfg.precision = "bfloat16"
tr = Trainer(cfg, {"data": {"pixel": (3,32,32), "label": ()}}, log_fn=lambda s: None)
params, opt_state = tr.init(seed=0)
rng = np.random.default_rng(0)
batch = {"data": {"pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
                  "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
key = jax.random.PRNGKey(0)
params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, ITERS)
hard_sync(params)
ts = []
for r in range(6):
    t0 = time.perf_counter()
    params, opt_state, _ = tr.train_steps(params, opt_state, batch, ITERS, key, ITERS)
    hard_sync(params)
    ts.append((time.perf_counter()-t0)/ITERS)
fl = net_train_flops(tr.train_net)
best = min(ts)
print(json.dumps({"variant": "recompute_s", "best_ms": round(best*1e3,3),
                  "mfu": round(mfu(fl, best) or 0, 4)}))
