import sys
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
from singa_tpu.ops.lrn_pallas import relu_lrn, _relu_lrn_2d
from singa_tpu.ops.lrn import lrn

k = jax.random.PRNGKey(0)
x = jax.random.normal(k, (2, 8, 8, 64), jnp.float32)
g = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 64), jnp.float32)
# oracle: relu then NCHW lrn
def oracle(z, relu):
    a = jnp.maximum(z, 0.0) if relu else z
    return lrn(jnp.transpose(a, (0,3,1,2)), 5, 1e-4, 0.75, 1.0, "NCHW").transpose(0,2,3,1)
for relu in (False, True):
    f = lambda z: relu_lrn(z, 5, 1e-4, 0.75, 1.0, relu=relu)
    o = lambda z: oracle(z, relu)
    y1, y2 = f(x), o(x)
    print("relu=", relu, "fwd", float(jnp.max(jnp.abs(y1-y2))))
    d1 = jax.vjp(f, x)[1](g)[0]
    d2 = jax.vjp(o, x)[1](g)[0]
    print("relu=", relu, "bwd", float(jnp.max(jnp.abs(d1-d2))))
print("backend:", jax.default_backend())
