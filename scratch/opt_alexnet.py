import json, sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full
from singa_tpu.utils.flops import mfu
from singa_tpu.utils.profiler import hard_sync
import singa_tpu.ops as ops
import singa_tpu.ops.lrn as lrn_mod
import singa_tpu.ops.pool as pool_mod
import singa_tpu.ops.dropout as drop_mod
import singa_tpu.core.layers as L
import singa_tpu.core.net as netmod

BS, ITERS = 2048, 20
MODEL_TFLOPS = 3.1211e12

# ---- candidate 1: bf16 LRN (no f32 norm), with optional custom_vjp ----
def _band(c, local_size, dtype):
    idx = jnp.arange(c)
    return (jnp.abs(idx[:, None] - idx[None, :]) <= local_size // 2).astype(dtype)

def lrn_bf16(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, layout="NCHW"):
    if layout != "NHWC":
        return lrn_mod.lrn(x, local_size, alpha, beta, knorm, layout)
    sq = jnp.square(x)
    norm = jnp.dot(sq, _band(x.shape[-1], local_size, x.dtype))
    norm = norm * jnp.asarray(alpha / local_size, x.dtype) + jnp.asarray(knorm, x.dtype)
    r = lax.rsqrt(norm)
    return x * (r * jnp.sqrt(r))

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,2,3,4,5))
def lrn_cvjp(x, local_size=5, alpha=1.0, beta=0.75, knorm=1.0, layout="NCHW"):
    return lrn_bf16(x, local_size, alpha, beta, knorm, layout)

def _lrn_fwd(x, local_size, alpha, beta, knorm, layout):
    sq = jnp.square(x)
    norm = jnp.dot(sq, _band(x.shape[-1], local_size, x.dtype))
    norm = norm * jnp.asarray(alpha/local_size, x.dtype) + jnp.asarray(knorm, x.dtype)
    r = lax.rsqrt(norm)
    p = r * jnp.sqrt(r)          # n^{-3/4}
    return x * p, (x, norm, p)

def _lrn_bwd(local_size, alpha, beta, knorm, layout, res, g):
    x, norm, p = res
    # dx = g*p - 2*beta*(alpha/L) * x * B^T(g * x * p / norm)
    t = g * x * p / norm
    bt = _band(x.shape[-1], local_size, x.dtype)
    s = jnp.dot(t, bt)
    dx = g * p - jnp.asarray(2*beta*alpha/local_size, x.dtype) * x * s
    return (dx,)
lrn_cvjp.defvjp(_lrn_fwd, _lrn_bwd)

# ---- candidate 2: max pool via shifted strided slices ----
def max_pool_slices(x, kernel, stride, layout="NCHW"):
    h, w = pool_mod._spatial(x, layout)
    ph, pw = pool_mod._ceil_pad(h, kernel, stride), pool_mod._ceil_pad(w, kernel, stride)
    oh, ow = pool_mod.pooled_size(h, kernel, stride), pool_mod.pooled_size(w, kernel, stride)
    neg = jnp.asarray(-jnp.inf, x.dtype)
    if layout == "NHWC":
        xp = jnp.pad(x, ((0,0),(0,ph),(0,pw),(0,0)), constant_values=neg)
        out = None
        for ki in range(kernel):
            for kj in range(kernel):
                sl = lax.slice(xp, (0, ki, kj, 0),
                               (xp.shape[0], ki+(oh-1)*stride+1, kj+(ow-1)*stride+1, xp.shape[3]),
                               (1, stride, stride, 1))
                out = sl if out is None else jnp.maximum(out, sl)
        return out
    return pool_mod.max_pool2d(x, kernel, stride, layout)

# ---- candidate 3: dropout via rbg hardware bits ----
def dropout_rbg(x, rate, rng, train=True):
    if not train or rate <= 0.0:
        return x
    pkeep = 1.0 - rate
    kd = jax.random.key_data(rng).astype(jnp.uint32).reshape(-1)
    key = jnp.concatenate([kd, kd])[:4]
    bits, _ = lax.rng_bit_generator(key, x.shape, dtype=jnp.uint32), None
    bits = bits[1] if isinstance(bits, tuple) else bits
    thresh = np.uint32(int(pkeep * (2**32 - 1)))
    mask = (bits < thresh).astype(x.dtype) / jnp.asarray(pkeep, x.dtype)
    return x * mask

def timeit(mods, no_remat=False):
    # monkeypatch
    orig = (ops.lrn, L.ops.lrn, ops.max_pool2d, L.ops.max_pool2d, ops.dropout, L.ops.dropout)
    if "lrn_bf16" in mods: ops.lrn = L.ops.lrn = lrn_bf16
    if "lrn_cvjp" in mods: ops.lrn = L.ops.lrn = lrn_cvjp
    if "pool" in mods: ops.max_pool2d = L.ops.max_pool2d = max_pool_slices
    if "drop" in mods: ops.dropout = L.ops.dropout = dropout_rbg
    try:
        cfg = alexnet_cifar10_full(batchsize=BS)
        cfg.precision = "bfloat16"
        tr = Trainer(cfg, {"data": {"pixel": (3,32,32), "label": ()}}, log_fn=lambda s: None)
        if no_remat:
            tr.train_net.remat_types = set()
            if tr.test_net: tr.test_net.remat_types = set()
        params, opt_state = tr.init(seed=0)
        rng = np.random.default_rng(0)
        batch = {"data": {
            "pixel": jax.device_put(rng.standard_normal((BS,3,32,32)).astype(np.float32)),
            "label": jax.device_put(rng.integers(0,10,(BS,)).astype(np.int32))}}
        key = jax.random.PRNGKey(0)
        params, opt_state, _ = tr.train_steps(params, opt_state, batch, 0, key, ITERS)
        hard_sync(params)
        t0 = time.perf_counter()
        params, opt_state, _ = tr.train_steps(params, opt_state, batch, ITERS, key, ITERS)
        hard_sync(params)
        return (time.perf_counter()-t0)/ITERS
    finally:
        ops.lrn, L.ops.lrn, ops.max_pool2d, L.ops.max_pool2d, ops.dropout, L.ops.dropout = orig

for name, mods, nr in [
    ("baseline", [], False),
    ("lrn_bf16_noremat", ["lrn_bf16"], True),
    ("lrn_cvjp", ["lrn_cvjp"], True),
    ("pool_slices", ["pool"], False),
    ("drop_rbg", ["drop"], False),
    ("all", ["lrn_cvjp","pool","drop"], True),
    ("all_bf16lrn", ["lrn_bf16","pool","drop"], True),
]:
    try:
        s = timeit(mods, nr)
        print(json.dumps({"variant": name, "step_ms": round(s*1e3,3),
                          "mfu": round(mfu(MODEL_TFLOPS, s) or 0, 4)}))
    except Exception as e:
        print(json.dumps({"variant": name, "error": repr(e)[:300]}))
