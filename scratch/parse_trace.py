import sys, glob, json
from tensorboard_plugin_profile.convert import raw_to_tool_data as rd
xp = glob.glob("/root/repo/scratch/trace/plugins/profile/*/*.xplane.pb")
xp.sort()
xp = xp[-1:]
params = {"graph_viewer_options": {}}
try:
    data, _ = rd.xspace_to_tool_data(xp, "op_profile", params)
    d = json.loads(data)
    # walk tree: byProgram or byCategory
    def walk(node, depth=0, out=None):
        m = node.get("metrics", {})
        name = node.get("name","")
        t = m.get("time", 0)
        if depth <= 3 and t:
            out.append((t, depth, name, m.get("flops",0)))
        for ch in node.get("children", []):
            walk(ch, depth+1, out)
    out = []
    root = d.get("byCategory") or d.get("byProgram")
    walk(root, 0, out)
    for t, depth, name, fl in out[:80]:
        print(f"{'  '*depth}{name}: time={t:.4f} flops={fl:.4f}")
except Exception as e:
    print("op_profile failed:", repr(e)[:500])
