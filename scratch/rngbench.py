import sys, time, json
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from singa_tpu.utils.profiler import hard_sync

shape = (2048, 4096)
key = jax.random.PRNGKey(0)

@jax.jit
def tf_mask(k):
    return (jax.random.uniform(k, shape) < 0.5).astype(jnp.bfloat16)

@jax.jit
def rbg_mask(k):
    kd = jax.random.key_data(k).astype(jnp.uint32).reshape(-1)
    key4 = jnp.tile(kd, 2)[:4]
    _, bits = lax.rng_bit_generator(key4, shape, dtype=jnp.uint32)
    return (bits < np.uint32(2**31)).astype(jnp.bfloat16)

@jax.jit
def tf_bits_mask(k):
    bits = jax.random.bits(k, shape, dtype=jnp.uint32)
    return (bits < np.uint32(2**31)).astype(jnp.bfloat16)

for name, fn in [("threefry_uniform", tf_mask), ("threefry_bits", tf_bits_mask), ("rbg", rbg_mask)]:
    out = fn(key); hard_sync(out)
    t0 = time.perf_counter()
    for i in range(50):
        out = fn(jax.random.fold_in(key, i))
    hard_sync(out)
    dt = (time.perf_counter()-t0)/50
    print(json.dumps({"rng": name, "ms": round(dt*1e3, 4)}))
