#!/usr/bin/env bash
# Feed-pipeline perf smoke (ISSUE 2 satellite): run the LeNet bench
# loop with the DeviceFeeder ON vs OFF at the same scan_chunk and
# record steps/sec plus the host data-wait fraction of step time in
# BENCH_pr2.json — the first point of the bench trajectory for the
# overlapped feed path.  The acceptance property is a measurable
# host-wait-fraction drop with the feeder enabled (the `value` field).
#
# Usage: scripts/perf_smoke.sh [out.json]     (CPU-only, no data)
# CI: pytest -m perf runs the same leg via tests/test_perf_smoke.py.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-BENCH_pr2.json}"
export JAX_PLATFORMS=cpu

python bench.py --feed-smoke --out "$OUT"

python - "$OUT" <<'EOF'
import json, sys
with open(sys.argv[1]) as f:
    r = json.load(f)
on, off = r["feeder_on"], r["feeder_off"]
print(f"feeder off: {off['steps_per_sec']} steps/s, "
      f"host-wait {off['host_wait_fraction']:.1%}")
print(f"feeder on : {on['steps_per_sec']} steps/s, "
      f"host-wait {on['host_wait_fraction']:.1%}")
assert r["value"] > 0, (
    f"host-wait fraction did not drop with the feeder enabled: "
    f"off={off['host_wait_fraction']} on={on['host_wait_fraction']}")
print(f"PERF SMOKE PASS: host-wait fraction dropped by {r['value']:.1%}")
EOF
