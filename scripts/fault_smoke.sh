#!/usr/bin/env bash
# Fault-tolerance smoke (ISSUE 1 satellite): a 20-step synthetic-data
# training run under a seeded FaultSchedule — one mid-run preemption
# plus one corrupt record — must be recovered by the Supervisor to the
# SAME final loss as an uninterrupted run (float tolerance; the config
# is dropout-free so the trajectories are bit-identical in practice).
#
# Usage: scripts/fault_smoke.sh        (CPU-only, no data, ~30s)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

python - <<'EOF'
import os
import tempfile

import numpy as np

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.supervisor import Supervisor
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.pipeline import prefetch
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils.faults import Backoff, FaultSchedule, inject

STEPS = 20
SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def cfg():
    return model_config_from_dict({
        "name": "fault-smoke", "train_steps": STEPS,
        "checkpoint_frequency": 5,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
             "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip1", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 32},
             "param": [{"name": "w1",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b1"}]},
            {"name": "ip2", "type": "kInnerProduct", "srclayers": "ip1",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w2",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b2"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip2", "label"]}]}})


def data_factory():
    # prefetch-wrapped so the data.decode fault site (and quarantine)
    # is on the path, exactly as resolve_data_source wires it
    return prefetch(synthetic_image_batches(8, seed=7, stream_seed=111))


def run_baseline():
    losses = []
    tr = Trainer(cfg(), SHAPES, log_fn=lambda s: None, donate=False)
    p, o = tr.init(seed=0)
    tr.run(p, o, data_factory(), seed=0,
           hooks=[lambda s, m: losses.append(float(m["loss"]))])
    return losses


def run_supervised(workspace):
    losses = {}
    tr = Trainer(cfg(), SHAPES, log_fn=print, donate=False)
    sup = Supervisor(tr, workspace, max_restarts=3,
                     backoff=Backoff(base=0.05, cap=0.2, seed=0),
                     log=print)
    # one corrupt record early (quarantined, stream continues in
    # order) + one preemption at step 12 (restore step-10 snapshot,
    # replay steps 10..19)
    sched = FaultSchedule.parse(
        "data.decode@4:corrupt,step.train@12:preempt", seed=0)
    with inject(sched):
        sup.run(data_factory, seed=0,
                hooks=[lambda s, m: losses.__setitem__(
                    s, float(m["loss"]))])
    assert [f.kind for f in sup.failures] == ["preemption"], sup.failures
    assert {f.site for f in sched.fired} == \
        {"data.decode", "step.train"}, sched.fired
    return [losses[s] for s in range(STEPS)]


base = run_baseline()
with tempfile.TemporaryDirectory(prefix="fault_smoke_") as ws:
    sup = run_supervised(ws)

final_base, final_sup = base[-1], sup[-1]
print(f"final loss: uninterrupted {final_base:.6f}  "
      f"supervised {final_sup:.6f}")
assert np.isfinite(final_sup)
assert abs(final_base - final_sup) <= 1e-5 * max(1.0, abs(final_base)), \
    (final_base, final_sup)
# the whole per-step trajectory matches, not just the endpoint
np.testing.assert_allclose(sup, base, rtol=1e-5, atol=1e-6)
print("FAULT SMOKE PASS: recovered run matches the uninterrupted one")
EOF

# Divergence-rescue leg (ISSUE 3): a silent NaN injected into the
# gradients after a good checkpoint must be detected by the health
# monitor, rolled back past (skip_unhealthy restore), and the recovered
# trajectory must match the uninterrupted run bit-for-bit.
python - <<'EOF'
import tempfile

import numpy as np

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.supervisor import Supervisor
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.pipeline import prefetch
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils.faults import Backoff, FaultSchedule, inject
from singa_tpu.utils.health import HealthMonitor, HealthSpec

STEPS = 20
SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def cfg():
    return model_config_from_dict({
        "name": "divergence-smoke", "train_steps": STEPS,
        "checkpoint_frequency": 5,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
             "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip1", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 32},
             "param": [{"name": "w1",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b1"}]},
            {"name": "ip2", "type": "kInnerProduct", "srclayers": "ip1",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w2",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b2"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip2", "label"]}]}})


def data_factory():
    return prefetch(synthetic_image_batches(8, seed=7, stream_seed=111))


tr0 = Trainer(cfg(), SHAPES, log_fn=lambda s: None, donate=False)
p, o = tr0.init(seed=0)
p_ref, _, _ = tr0.run(p, o, data_factory(), seed=0)

mon = HealthMonitor(HealthSpec(), log_fn=print)
tr = Trainer(cfg(), SHAPES, log_fn=print, donate=False, health=mon)
with tempfile.TemporaryDirectory(prefix="divergence_smoke_") as ws:
    sup = Supervisor(tr, ws, max_restarts=0,
                     backoff=Backoff(base=0.05, cap=0.2, seed=0),
                     log=print)
    sched = FaultSchedule.parse("step.grad@12:nan", seed=0)
    with inject(sched):
        p_sup, _, _ = sup.run(data_factory, seed=0)
assert [f.kind for f in sup.failures] == ["divergence"], sup.failures
assert {f.site for f in sched.fired} == {"step.grad"}, sched.fired
for k in p_ref:
    assert np.all(np.isfinite(np.asarray(p_sup[k]))), k
    np.testing.assert_array_equal(np.asarray(p_sup[k]),
                                  np.asarray(p_ref[k]), err_msg=k)
print("DIVERGENCE SMOKE PASS: NaN detected, rolled back, recovered "
      "run matches the uninterrupted one bit-for-bit")
EOF

# CLI leg: the same machinery through singa_tpu.main's --max-restarts /
# --fault_spec flags (synthetic data, supervised, one preemption)
WS=$(mktemp -d -t fault_smoke_cli_XXXX)
CLEAN_LOG=$(mktemp -t fault_smoke_clean_XXXX)
trap 'rm -rf "$WS" "$CLEAN_LOG"' EXIT
python -m singa_tpu.main -model_conf examples/mnist/mlp.conf \
    --synthetic --steps 20 --workspace "$WS" \
    --max-restarts 3 --fault_spec "step.train@8:preempt" \
    | grep -E "fault injection active|supervisor|training done" || {
        echo "FAULT SMOKE CLI LEG FAILED"; exit 1; }
echo "FAULT SMOKE CLI PASS"

# Clean-run leg: with the health sentinel on and NO injection, nothing
# may be flagged as poisoned and no divergence rescue may fire — a
# false positive here would reject healthy sync rounds / checkpoints in
# production.
rm -rf "$WS"; mkdir -p "$WS"
python -m singa_tpu.main -model_conf examples/mnist/mlp.conf \
    --synthetic --steps 20 --workspace "$WS" --health on \
    --max-restarts 3 > "$CLEAN_LOG" 2>&1 || {
        cat "$CLEAN_LOG"; echo "CLEAN HEALTH RUN FAILED"; exit 1; }
if grep -E "warning: .*poisoned|divergence|NONFINITE|refusing checkpoint" \
        "$CLEAN_LOG"; then
    echo "CLEAN HEALTH RUN FLAGGED FALSE POSITIVES"; exit 1
fi
grep -q "training done" "$CLEAN_LOG" || {
    cat "$CLEAN_LOG"; echo "CLEAN HEALTH RUN DID NOT FINISH"; exit 1; }
echo "CLEAN HEALTH RUN PASS: zero poisoned/divergence flags"

# Pipeline leg (ISSUE 10): the closed train-and-serve loop under the
# same injected preemption — the supervisor absorbs the kill while the
# fleet keeps serving; the subcommand exits non-zero unless the loop
# drained (every blessed checkpoint promoted, zero failed requests).
PWS=$(mktemp -d -t fault_smoke_pipeline_XXXX)
trap 'rm -rf "$WS" "$CLEAN_LOG" "$PWS"' EXIT
python -m singa_tpu.main pipeline \
    -model_conf examples/transformer/lm_tiny.conf \
    --workspace "$PWS" --synthetic --smoke 20 \
    --fault_spec "step.train@20:preempt" \
    | grep -E '"lag_steps": 0' > /dev/null || {
        echo "FAULT SMOKE PIPELINE LEG FAILED"; exit 1; }
echo "FAULT SMOKE PIPELINE PASS: preempted trainer invisible to traffic"
