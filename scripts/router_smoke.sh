#!/usr/bin/env bash
# Router smoke (ISSUE 19 acceptance): crash-safe control plane — the
# durable session WAL, router restart, and zero-downtime handoff — on
# CPU.  FAILS unless
#   * SIGKILL-ing the primary router mid-decode of 3 concurrent
#     256-token streams (a REAL subprocess, over HTTP) costs ZERO
#     client-visible failures: every client reconnects with its
#     session id + resume_from and splices exactly-once, zero
#     duplicate and zero missing indices, BIT-IDENTICAL to an
#     uninterrupted reference;
#   * a POST /admin/handoff lame-ducks the primary (in-flight streams
#     finish; fresh admissions get 409 + the successor URL) and the
#     promoted `--standby` router serves bit-identically under the
#     next epoch, the old primary's WAL fenced;
#   * quarantine benches and per-(tenant, class) Retry-After streaks
#     survive the restart (no strike laundering);
#   * the WAL costs <= 3% of p50 streaming tok/s (interleaved A/B vs
#     wal=off);
#   * an injected `router.wal` fault degrades to counted lost
#     durability (`wal_lost`) with the stream still completing.
# Writes BENCH_pr19.json (per-leg ledgers and a `gates` dict).
#
# Usage: scripts/router_smoke.sh        (CPU-only, no data, ~4 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — real-SIGKILL restart, HTTP handoff, state
# survival, WAL overhead A/B, WAL fault.  bench_router_smoke raises
# (and this script fails) unless every acceptance bullet holds.
python bench.py --router-smoke --out BENCH_pr19.json

# the recorded artifact must actually carry the numbers, not nulls,
# and every gate it records must have passed
python - <<'EOF'
import json
with open("BENCH_pr19.json") as f:
    d = json.loads(f.read())
rl = d["restart_leg"]
assert rl["failures"] == 0 and rl["dup"] == 0 and rl["missing"] == 0, d
assert rl["parity_mismatch"] == 0 and rl["recovered"] >= 3, d
assert rl["epoch_after_restart"] >= 2, d
hl = d["handoff_leg"]
assert hl["failures"] == 0 and hl["parity_mismatch"] == 0, d
assert hl["refusal_points_successor"] == 1 and hl["promoted_epoch"] >= 2, d
sl = d["state_leg"]
assert sl["quarantine_survived"] == 1 and sl["shed_streak_survived"] == 1, d
ol = d["overhead_leg"]
assert ol["ratio"] >= 0.97, d
fl = d["wal_fault_leg"]
assert fl["wal_lost"] >= 1 and fl["stream_ok"] == 1, d
gates = d.get("gates")
assert isinstance(gates, dict) and gates, "gates dict missing"
bad = [k for k, g in gates.items() if not g.get("pass")]
assert not bad, f"gates failed: {bad}"
print(f"BENCH_pr19.json ok: {rl['recovered']} streams x "
      f"{d['stream_tokens']} tokens outlived a router SIGKILL "
      f"(0 dup/missing, bit-identical), handoff promoted epoch "
      f"{hl['promoted_epoch']} with zero loss, WAL overhead ratio "
      f"{ol['ratio']}")
EOF
echo "ROUTER BENCH PASS: the control plane outlived its process — the"
echo "  splice was exactly-once, the handoff lost nothing, strikes held"

# Leg 2: the regression suite — WAL roundtrip/torn-tail/fencing,
# replay-only terminal sessions, bounded retention, lame-duck
# refusals, control-state restore, reload-poll supervision, fd-flat
# handle churn, in-process restart + handoff over real engines.
python -m pytest tests/test_router_wal.py -q -m wal -p no:cacheprovider

# Leg 3: the offline validator — a deliberately torn journal must
# summarize as survivable (torn_tail true, prefix intact), not error.
python - <<'EOF'
import json
import subprocess
import sys
import tempfile

from singa_tpu.serve.sessionlog import SessionWal, wal_path

d = tempfile.mkdtemp(prefix="walcheck_smoke_")
w = SessionWal(d, 1, group_tokens=2, group_ms=5.0,
               log_fn=lambda s: None)
w.append_open("s1-1", [5, 6], 8, "interactive", "default", None, 1,
              None)
for i in range(4):
    w.append_tok("s1-1", i, 10 + i)
w.close()
with open(wal_path(d, 1), "ab") as f:
    f.write(b'{"c": 1, "r": {"k": "tok", "sid"')     # the torn tail
out = subprocess.run(
    [sys.executable, "tools/walcheck.py", d],
    capture_output=True, text=True)
assert out.returncode == 0, out.stderr
got = json.loads(out.stdout)
assert got["torn_tail"] is True and got["epoch"] == 1, got
assert got["live_sessions"] == 1 and got["journaled_tokens"] == 4, got
print(f"walcheck ok: torn tail summarized as survivable "
      f"({got['records']} records, {got['journaled_tokens']} tokens)")
EOF
echo "WALCHECK PASS: the offline validator reads what replay would"

# Leg 4: the report — BENCH_pr19.json lands in the table and its
# recorded gates are re-checked (missing/failing gates exit non-zero).
python tools/bench_report.py | grep -E 'BENCH_pr19' > /dev/null || {
    echo "BENCH REPORT LEG FAILED"; exit 1; }
python tools/bench_report.py
echo "ROUTER SMOKE PASS"
