#!/usr/bin/env bash
# Transport smoke (ISSUE 20 acceptance): the zero-copy binary wire
# protocol against the HTTP/JSON debug surface — on CPU.  FAILS unless
#   * closed-loop unary decodes over one persistent binary connection
#     beat the keep-alive HTTP handle on p50, and the `singa_wire_*`
#     serialization-time split shows the binary encode path cheaper
#     than the JSON path (where the saved time comes from);
#   * the streamed token sequence is BIT-IDENTICAL across transports;
#   * killing the binary-capable engine of a mixed fleet mid-stream
#     splices the remainder from the HTTP-only sibling exactly once;
#   * frame fuzz (garbage magic, truncations at every cut point,
#     oversized length prefixes, random bytes) is a counted
#     `wire_malformed_total` close — never a hang, never a crash;
#   * injected `wire.frame` drop/corrupt/tear is absorbed by the
#     negotiating handle's HTTP fallback with zero client-visible
#     failures.
# Writes BENCH_pr20.json (per-leg numbers and a `gates` dict).
#
# Usage: scripts/transport_smoke.sh       (CPU-only, no data, ~3 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — A/B, parity, splice, fuzz and fault legs
# over real engines.  bench_transport_smoke raises (and this script
# fails) unless every acceptance bullet holds.
python bench.py --transport-smoke --out BENCH_pr20.json

# the recorded artifact must actually carry the numbers, not nulls,
# and every gate it records must have passed
python - <<'EOF'
import json
with open("BENCH_pr20.json") as f:
    d = json.loads(f.read())
ab = d["ab_leg"]
assert ab["binary_p50_ms"] < ab["http_p50_ms"], d
assert ab["binary_ser_us"] < ab["http_ser_us"], d
assert d["parity_leg"]["mismatch"] == 0, d
sp = d["splice_leg"]
assert sp["failures"] == 0 and sp["dup"] == 0, d
assert sp["missing"] == 0 and sp["parity_mismatch"] == 0, d
assert sp["transport_before_kill"] == "binary", d
fz = d["fuzz_leg"]
assert fz["hangs"] == 0 and fz["listener_survived"] == 1, d
assert fz["malformed_counted"] >= fz["cases"] - 2, d
fl = d["fault_leg"]
assert fl["client_failures"] == 0 and fl["faulted_frames"] >= 3, d
gates = d.get("gates")
assert isinstance(gates, dict) and gates, "gates dict missing"
bad = [k for k, g in gates.items() if not g.get("pass")]
assert not bad, f"gates failed: {bad}"
print(f"BENCH_pr20.json ok: binary p50 {ab['binary_p50_ms']}ms vs "
      f"HTTP {ab['http_p50_ms']}ms, wire encode {ab['binary_ser_us']}"
      f"us vs JSON {ab['http_ser_us']}us per stream, splice "
      f"exactly-once over the transport boundary, {fz['cases']} fuzz "
      f"cases closed without a hang, wire.frame x3 absorbed")
EOF
echo "TRANSPORT BENCH PASS: the binary path is faster, bit-identical,"
echo "  and dies politely — fuzz closes, faults fall back to HTTP"

# Leg 2: the regression suite — frame-codec roundtrips and fuzz
# hardening, TokenRing semantics, multiplexed persistent connections,
# negotiation/fallback, cross-transport failover, wire.frame
# absorption, HTTP keep-alive reuse.
python -m pytest tests/test_wire.py -q -m wire -p no:cacheprovider

# Leg 3: the report — BENCH_pr20.json lands in the table and its
# recorded gates are checked (missing/failing gates exit non-zero).
python tools/bench_report.py | grep -E 'BENCH_pr20' > /dev/null || {
    echo "BENCH REPORT LEG FAILED"; exit 1; }
python tools/bench_report.py
echo "TRANSPORT SMOKE PASS"
