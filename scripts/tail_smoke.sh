#!/usr/bin/env bash
# Tail smoke (ISSUE 12 acceptance): tail-tolerant serving — deadlines,
# hedged dispatch with a retry budget, priority-aware brownout — on
# CPU.  FAILS unless
#   * with one stalled engine in a 3-engine fleet, hedged p99 is at
#     most HALF the unhedged p99 (>= 2x tail cut) while hedges stay
#     <= 10% of routed traffic (the retry-budget bound, observed);
#   * under open-loop overload with a 1:1:1
#     interactive/batch/best_effort mix, retry amplification
#     (attempts/routed) stays <= 1.2x and interactive p95 holds the
#     SLO while best_effort sheds (brownout engaged, honest
#     Retry-After);
#   * requests whose deadline expired before arrival are refused as
#     `expired_on_arrival` and burn ZERO engine steps.
# Writes BENCH_pr12.json (both p99s, hedge rate, amplification,
# per-class sheds/latency, DOA accounting, and a `gates` dict).
#
# Usage: scripts/tail_smoke.sh        (CPU-only, no data, ~2 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — hedge contrast, brownout under overload,
# dead-on-arrival accounting.  bench_tail_smoke raises (and this
# script fails) unless every acceptance bullet holds.
python bench.py --tail-smoke --out BENCH_pr12.json

# the recorded artifact must actually carry the numbers, not nulls,
# and every gate it records must have passed
python - <<'EOF'
import json
with open("BENCH_pr12.json") as f:
    d = json.loads(f.read())
for k in ("value", "hedged_p99_ms", "unhedged_p99_ms", "hedge_rate",
          "retry_amplification", "interactive_p95_ms",
          "best_effort_sheds", "expired_on_arrival"):
    assert isinstance(d.get(k), (int, float)), \
        f"BENCH_pr12.json: {k} missing/null: {d.get(k)}"
assert d["value"] <= 0.5, d
assert d["hedge_rate"] <= 0.10, d
assert d["retry_amplification"] <= 1.2, d
assert d["interactive_p95_ms"] <= d["slo_p95_ms"], d
assert d["best_effort_sheds"] >= 1 and d["brownout_sheds"] >= 1, d
assert d["expired_on_arrival"] >= 1 and d["doa_steps_burned"] == 0, d
gates = d.get("gates")
assert isinstance(gates, dict) and gates, "gates dict missing"
bad = [k for k, g in gates.items() if not g.get("pass")]
assert not bad, f"gates failed: {bad}"
print(f"BENCH_pr12.json ok: hedged p99={d['hedged_p99_ms']}ms vs "
      f"unhedged {d['unhedged_p99_ms']}ms ({d['value']}x), hedge "
      f"rate {d['hedge_rate']}, amplification "
      f"{d['retry_amplification']}x, interactive p95="
      f"{d['interactive_p95_ms']}ms (SLO {d['slo_p95_ms']}ms), "
      f"best_effort sheds {d['best_effort_sheds']}, DOA "
      f"{d['expired_on_arrival']} at 0 engine steps")
EOF
echo "TAIL BENCH PASS: the straggler paid for itself, the budget held,"
echo "  interactive held its SLO while best_effort browned out"

# Leg 2: the regression suite — deadline propagation, hedge win/cancel,
# budget exhaustion, brownout ordering, Retry-After escalation,
# per-class stats, DOA zero-step accounting.
python -m pytest tests/test_tail.py -q -m tail -p no:cacheprovider

# Leg 3: the report — every BENCH_pr*.json lands in one table, the new
# artifact is in it, and its recorded gates are checked (a listed
# artifact with missing/failing gates exits non-zero).
python tools/bench_report.py | grep -E 'BENCH_pr12' > /dev/null || {
    echo "BENCH REPORT LEG FAILED"; exit 1; }
python tools/bench_report.py
echo "TAIL SMOKE PASS"
