#!/usr/bin/env bash
# Fleet smoke (ISSUE 7 acceptance): a 3-engine serving fleet behind
# the health-driven router, on CPU.  FAILS unless
#   * killing 1 of 3 engines under load costs ZERO client-visible
#     failures (requests retry onto healthy siblings or shed with
#     503 + Retry-After; never a 500, never a hang), the dead engine
#     is quarantined, and the revived engine is readmitted;
#   * a DIVERGED checkpoint is canaried on exactly one engine and
#     auto-rolled back (never >=2 engines on the bad fingerprint), and
#     a healthy checkpoint afterwards promotes fleet-wide.
# Writes BENCH_pr7.json (fleet p50/p95, kill-recovery time, rollout
# outcome counts).
#
# Usage: scripts/fleet_smoke.sh        (CPU-only, no data, ~3 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — in-process 3-engine fleet over real HTTP
# (FleetServer), kill/revive mid-load, diverged-then-healthy rollout.
# bench_fleet_smoke raises (and this script fails) unless every
# acceptance bullet holds.
python bench.py --fleet-smoke --out BENCH_pr7.json

# the recorded artifact must actually carry the numbers, not nulls
python - <<'EOF'
import json
with open("BENCH_pr7.json") as f:
    d = json.loads(f.read())
for k in ("value", "p95_latency_ms", "kill_recovery_s"):
    assert isinstance(d.get(k), (int, float)), \
        f"BENCH_pr7.json: {k} missing/null: {d.get(k)}"
assert d["quarantines"] >= 1 and d["readmissions"] >= 1, d
assert d["rollbacks"] == 1 and d["promotions"] == 1, d
assert d["final_steps"] == [3, 3, 3], d
print(f"BENCH_pr7.json ok: p50={d['value']}ms p95={d['p95_latency_ms']}ms "
      f"kill_recovery={d['kill_recovery_s']}s rollout="
      f"{d['canaries']}c/{d['promotions']}p/{d['rollbacks']}r")
EOF
echo "FLEET BENCH PASS: engine kill absorbed, diverged canary rolled"
echo "  back on one engine, healthy checkpoint promoted fleet-wide"

# Leg 2: the subprocess deployment — 3 real `serve --pinned` worker
# processes adopted via a hostfile, SIGKILL one mid-load (a REAL
# process death, not a simulated one), zero client-visible failures,
# quarantine, then restart -> readmission.
python - <<'EOF'
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

PORTS = [18471, 18472, 18473]
SPEC = "buckets=2x8,max_new_tokens=4,batch_window_s=0.005"


def spawn(port):
    return subprocess.Popen(
        [sys.executable, "-m", "singa_tpu.main", "serve",
         "-model_conf", "examples/transformer/lm.conf",
         "--pinned", "--port", str(port), "--serve_spec", SPEC],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def wait_ready(port, deadline_s=180):
    deadline = time.time() + deadline_s
    while True:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2)
            return
        except Exception:
            if time.time() > deadline:
                raise RuntimeError(f"worker on :{port} never came up")
            time.sleep(0.25)


procs = {p: spawn(p) for p in PORTS}
try:
    for p in PORTS:
        wait_ready(p)
    hostfile = tempfile.NamedTemporaryFile(
        mode="w", suffix=".hosts", delete=False)
    hostfile.write("".join(f"127.0.0.1:{p}\n" for p in PORTS))
    hostfile.close()

    from singa_tpu.serve import EngineFleet, RouterSpec
    fleet = EngineFleet.from_hostfile(
        hostfile.name,
        router_spec=RouterSpec(probe_period_s=0.1, quarantine_after=1,
                               readmit_base_s=0.1, readmit_cap_s=1.0),
        log_fn=lambda s: None)
    fleet.start()
    prompt = list(range(1, 6))
    for _ in range(6):
        fleet.generate(prompt)

    # SIGKILL one worker process mid-load: traffic must not notice
    victim = PORTS[0]
    procs[victim].send_signal(signal.SIGKILL)
    procs[victim].wait()
    failures = 0
    for _ in range(20):
        try:
            fleet.generate(prompt)
        except Exception:  # noqa: BLE001 — counted, asserted zero
            failures += 1
        time.sleep(0.02)
    assert failures == 0, f"{failures} client-visible failures after kill"
    assert fleet.router.stats.quarantines >= 1, "no quarantine"

    # restart the worker -> the router readmits it on a clean probe
    procs[victim] = spawn(victim)
    wait_ready(victim)
    deadline = time.time() + 30
    while time.time() < deadline and fleet.router.stats.readmissions == 0:
        time.sleep(0.1)
    assert fleet.router.stats.readmissions >= 1, "no readmission"
    fleet.stop()
    print(f"subprocess fleet ok: SIGKILL absorbed with 0 failures, "
          f"quarantines={fleet.router.stats.quarantines}, "
          f"readmissions={fleet.router.stats.readmissions}")
finally:
    for pr in procs.values():
        if pr.poll() is None:
            pr.kill()
EOF
echo "FLEET SUBPROCESS PASS: real worker SIGKILL absorbed, quarantine"
echo "  + readmission over the hostfile/HTTP membership"

# Leg 3: the CLI surface — `singa_tpu.main serve --fleet 3 --smoke`
python -m singa_tpu.main serve -model_conf examples/transformer/lm.conf \
    --fleet 3 --smoke 6 \
    --serve_spec 'buckets=2x8,max_new_tokens=4,batch_window_s=0.005' \
    | grep -E '"completed": 6' > /dev/null || {
        echo "FLEET SMOKE CLI LEG FAILED"; exit 1; }
echo "FLEET SMOKE CLI PASS"
