#!/usr/bin/env bash
# Serving-tier smoke (ISSUE 5 acceptance): concurrent clients against
# the HTTP frontend on CPU.  FAILS on any program recompile after
# warmup, any dropped/failed in-flight request across a mid-run
# checkpoint hot-reload, or if an injected serve.reload fault does not
# degrade to keep-serving-old-params (counted in ServeStats).  Writes
# BENCH_pr5.json (p50/p95 latency, occupancy, QPS).
#
# Usage: scripts/serve_smoke.sh        (CPU-only, no data, ~1 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — N concurrent HTTP clients, mid-run hot
# reload, injected serve.reload fault.  bench_serve_smoke raises (and
# this script fails) unless every acceptance bullet holds.
python bench.py --serve-smoke --out BENCH_pr5.json

# the recorded artifact must actually carry the latency/occupancy
# numbers, not nulls
python - <<'EOF'
import json
with open("BENCH_pr5.json") as f:
    d = json.loads(f.read())
for k in ("value", "p95_latency_ms", "batch_occupancy", "qps"):
    assert isinstance(d.get(k), (int, float)), f"BENCH_pr5.json: {k} missing/null: {d.get(k)}"
assert d["compiles_total"] == d["compiles_warmup"], d
assert d["reload_failures"] == 1 and d["reloads"] == 2, d
print(f"BENCH_pr5.json ok: p50={d['value']}ms p95={d['p95_latency_ms']}ms "
      f"occupancy={d['batch_occupancy']} qps={d['qps']}")
EOF
echo "SERVE SMOKE PASS: zero recompiles after warmup, hot reload with"
echo "  zero dropped in-flight requests, reload fault degraded + counted"

# Leg 2: padded-batch parity — a request served through a padded bucket
# must decode the EXACT tokens generate() produces unpadded (the
# left-pad + kmask contract, serve/engine.py).
python - <<'EOF'
import tempfile
import jax
import numpy as np
from singa_tpu.core.net import build_net
from singa_tpu.models.generate import generate
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.serve import InferenceEngine, InferenceServer, ServeSpec

cfg = transformer_lm(vocab_size=64, num_layers=2, embed_dim=32,
                     num_heads=4, head_dim=8, seq_len=16, batchsize=2)
net = build_net(cfg, "kTest", {"data": {"input": (16,), "target": (16,)}})
params = net.init_params(jax.random.PRNGKey(0))
spec = ServeSpec(buckets=((4, 12),), max_new_tokens=8,
                 batch_window_s=0.005)
engine = InferenceEngine(net, spec, params=params, log_fn=lambda s: None)
with InferenceServer(engine, http=False, log_fn=lambda s: None) as srv:
    rng = np.random.default_rng(3)
    for plen in (1, 5, 12):
        prompt = rng.integers(1, 64, plen).astype(np.int32)
        ref = np.asarray(generate(net, params, prompt[None], 8))[0]
        got = srv.generate(prompt)["tokens"]
        assert got == ref.tolist(), (plen, got, ref.tolist())
print("SERVE PARITY PASS: padded bucket decode == unpadded generate()")
EOF

# Leg 3: the CLI surface — `singa_tpu.main serve --smoke` end to end
python -m singa_tpu.main serve -model_conf examples/transformer/lm.conf \
    --smoke 5 \
    --serve_spec 'buckets=2x8/4x16,max_new_tokens=6,batch_window_s=0.005' \
    | grep -E '"completed": 5' > /dev/null || {
        echo "SERVE SMOKE CLI LEG FAILED"; exit 1; }
echo "SERVE SMOKE CLI PASS"

# Leg 4 (ISSUE 8 acceptance): continuous batching vs the static bucket
# path under the same mixed load over real HTTP.  bench_cb_smoke raises
# (and this script fails) unless a short request completes while a long
# generation still decodes, cb p95 <= 0.5x static p95, and both legs
# compile O(1) programs at warmup with zero recompiles after.  Writes
# BENCH_pr8.json.
python bench.py --cb-smoke --out BENCH_pr8.json

python - <<'EOF'
import json
with open("BENCH_pr8.json") as f:
    d = json.loads(f.read())
assert d["value"] <= d["gate"], f"cb p95 ratio {d['value']} > gate {d['gate']}"
assert d["short_completed_while_long_decoding"] is True, d
for leg in ("static", "cb"):
    for k in ("p50_ms", "p95_ms", "p99_ms", "tokens_per_s_p50"):
        v = d[leg][k]
        assert isinstance(v, (int, float)), f"BENCH_pr8.json: {leg}.{k} missing/null: {v}"
    assert d[leg]["compiles_total"] == d[leg]["compiles_warmup"], d[leg]
for k in ("slot_occupancy", "block_utilization"):
    assert isinstance(d["cb"][k], (int, float)) and 0 < d["cb"][k] <= 1, (k, d["cb"][k])
assert d["cb"]["compiles_warmup"] == 2, d["cb"]  # one prefill + one decode
print(f"BENCH_pr8.json ok: cb p95 {d['cb']['p95_ms']}ms vs static p95 "
      f"{d['static']['p95_ms']}ms (ratio {d['value']}), slot occupancy "
      f"{d['cb']['slot_occupancy']}, block utilization {d['cb']['block_utilization']}")
EOF
echo "CB SMOKE PASS: short completed mid-long-decode, cb p95 <= 0.5x static,"
echo "  O(1) warmup compiles, zero recompiles after"

# Leg 5: the cb CLI surface — the same serve --smoke driver through the
# continuous-batching path (scheduler slots instead of buckets)
python -m singa_tpu.main serve -model_conf examples/transformer/lm.conf \
    --smoke 5 \
    --serve_spec 'buckets=2x16,max_new_tokens=6,cb=on,cb_slots=2,cb_block_len=4' \
    | grep -E '"completed": 5' > /dev/null || {
        echo "SERVE SMOKE CB CLI LEG FAILED"; exit 1; }
echo "SERVE SMOKE CB CLI PASS"
