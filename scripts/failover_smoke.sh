#!/usr/bin/env bash
# Failover smoke (ISSUE 13 acceptance): mid-stream failover — durable
# decode sessions that survive engine death — on CPU.  FAILS unless
#   * SIGKILL-ing the engine serving >= 3 concurrent 1024-token
#     streams costs ZERO client-visible stream failures, zero
#     duplicate and zero missing token indices, and every spliced
#     output is BIT-IDENTICAL to an uninterrupted run;
#   * an injected `serve.resume` fault degrades the stream to the
#     pre-failover terminal error — never a hang, never a duplicate;
#   * a silently stalled engine (`engine.stall`) is caught by the
#     per-stream idle watchdog (`stream_idle_s`) and the stream
#     resumes on a sibling, still bit-identical.
# Writes BENCH_pr13.json (per-leg session ledgers and a `gates` dict).
#
# Usage: scripts/failover_smoke.sh        (CPU-only, no data, ~3 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — in-process fleets over real engines:
# kill / resume-fault / watchdog legs.  bench_failover_smoke raises
# (and this script fails) unless every acceptance bullet holds.
python bench.py --failover-smoke --out BENCH_pr13.json

# the recorded artifact must actually carry the numbers, not nulls,
# and every gate it records must have passed
python - <<'EOF'
import json
with open("BENCH_pr13.json") as f:
    d = json.loads(f.read())
kl = d["kill_leg"]
assert kl["failures"] == 0 and kl["dup"] == 0 and kl["missing"] == 0, d
assert kl["parity_mismatch"] == 0 and kl["spliced"] >= 1, d
rf = d["resume_fault_leg"]
assert rf["terminal"] == 1 and rf["dup"] == 0, d
assert rf["sessions"]["resume_faults"] >= 1, d
wd = d["watchdog_leg"]
assert wd["failures"] == 0 and wd["parity_mismatch"] == 0, d
assert wd["sessions"]["idle_timeouts"] >= 1, d
gates = d.get("gates")
assert isinstance(gates, dict) and gates, "gates dict missing"
bad = [k for k, g in gates.items() if not g.get("pass")]
assert not bad, f"gates failed: {bad}"
print(f"BENCH_pr13.json ok: {d['value']} streams x "
      f"{d['stream_tokens']} tokens survived the kill of "
      f"{d['victim']} ({kl['spliced']} spliced, 0 dup/missing), "
      f"resume fault degraded to the old terminal error, watchdog "
      f"caught the silent stall")
EOF
echo "FAILOVER BENCH PASS: the stream outlived its engine, the splice"
echo "  was exactly-once and bit-identical, the fault degraded honestly"

# Leg 2: the regression suite — exactly-once splice on stubs, stale
# fingerprint honesty, resume-off / fault / legacy-handle degradation,
# idle watchdog, drain-kick of a resumed stream, scheduler-level
# resume admission (fast 400 at zero engine steps), transport-budget
# deadline clamp.
python -m pytest tests/test_failover.py -q -m failover -p no:cacheprovider

# Leg 3: the subprocess deployment — 2 real `serve --pinned` worker
# processes (same conf + seed -> same fingerprint), a stream killed by
# a REAL SIGKILL mid-decode, spliced bit-identically onto the sibling.
python - <<'EOF'
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

PORTS = [18481, 18482]
SPEC = ("buckets=2x128,max_new_tokens=48,batch_window_s=0.005,"
        "cb=on,cb_slots=2,cb_block_len=16")


def spawn(port):
    return subprocess.Popen(
        [sys.executable, "-m", "singa_tpu.main", "serve",
         "-model_conf", "examples/transformer/lm.conf",
         "--pinned", "--port", str(port), "--serve_spec", SPEC],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def wait_ready(port, deadline_s=300):
    deadline = time.time() + deadline_s
    while True:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2)
            return
        except Exception:
            if time.time() > deadline:
                raise RuntimeError(f"worker on :{port} never came up")
            time.sleep(0.25)


procs = {p: spawn(p) for p in PORTS}
try:
    for p in PORTS:
        wait_ready(p)
    hostfile = tempfile.NamedTemporaryFile(
        mode="w", suffix=".hosts", delete=False)
    hostfile.write("".join(f"127.0.0.1:{p}\n" for p in PORTS))
    hostfile.close()

    from singa_tpu.serve import EngineFleet, RouterSpec
    fleet = EngineFleet.from_hostfile(
        hostfile.name,
        router_spec=RouterSpec(probe_period_s=0.1, quarantine_after=2,
                               request_timeout_s=120.0, hedge="off"),
        log_fn=lambda s: None)
    fleet.start()
    prompt = [5, 7, 9, 11]

    # reference: an uninterrupted stream (same fingerprint everywhere,
    # so WHICH worker serves it does not matter)
    ref = None
    for ev in fleet.generate_stream(prompt, max_new=48):
        if ev.get("done"):
            assert "error" not in ev, ev
            ref = ev["tokens"]
    assert ref is not None and len(ref) >= 16, ref

    # the failover stream: SIGKILL the worker actually serving it
    # after 8 delivered tokens — a REAL process death mid-decode
    seen, done = [], None
    for ev in fleet.generate_stream(prompt, max_new=48):
        if ev.get("done"):
            done = ev
            break
        seen.append((ev["i"], ev["token"]))
        if len(seen) == 8:
            sess = fleet.router.sessions.snapshot()["sessions"][0]
            victim = PORTS[int(sess["engine"].split("-")[1])]
            procs[victim].send_signal(signal.SIGKILL)
            procs[victim].wait()
    assert done is not None and "error" not in done, done
    idx = [i for i, _ in seen]
    assert idx == list(range(len(ref))), f"dup/missing indices: {idx}"
    assert [t for _, t in seen] == ref, "streamed tokens != reference"
    assert done["tokens"] == ref, "spliced terminal != reference"
    assert done.get("spliced") is True and done.get("resumes", 0) >= 1
    snap = fleet.router.sessions.snapshot()
    assert snap["resumed"] >= 1 and snap["failed"] == 0, snap
    fleet.stop()
    print(f"subprocess failover ok: SIGKILL of :{victim} mid-stream, "
          f"{len(ref)} tokens delivered exactly once, splice "
          f"bit-identical (resumes={done['resumes']})")
finally:
    for pr in procs.values():
        if pr.poll() is None:
            pr.kill()
EOF
echo "FAILOVER SUBPROCESS PASS: a real worker SIGKILL mid-stream,"
echo "  spliced bit-identically onto the surviving sibling"

# Leg 4: the report — BENCH_pr13.json lands in the table and its
# recorded gates are checked (missing/failing gates exit non-zero).
python tools/bench_report.py | grep -E 'BENCH_pr13' > /dev/null || {
    echo "BENCH REPORT LEG FAILED"; exit 1; }
python tools/bench_report.py
echo "FAILOVER SMOKE PASS"
