#!/usr/bin/env python
"""Seeded random-fault stream chaos (the nightly chaos.yml leg).

A random-rate `FaultSchedule` over the durable-stream harness: resume
attempts (`serve.resume`) and dispatches (`fleet.dispatch`) fail at
seed-chosen rates, and the engine serving stream 0 is killed at a
seed-derived token offset.  The invariant chaos must never break:
every stream either finishes with each index delivered exactly once,
or fails with a TERMINAL error — never a hang, never a duplicate,
never a sequence gap before the failure.

The seed comes from `FAULT_SEED` (chaos.yml derives it from the UTC
date, so every night exercises a different interleaving and a red
night reproduces locally with that day's seed):

    FAULT_SEED=20260805 JAX_PLATFORMS=cpu python scripts/chaos_streams.py
"""

import os
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from singa_tpu.core.net import build_net  # noqa: E402
from singa_tpu.models.transformer import transformer_lm  # noqa: E402
from singa_tpu.serve import (EngineFleet, RouterSpec,  # noqa: E402
                             ServeSpec)
from singa_tpu.utils.checkpoint import CheckpointManager  # noqa: E402
from singa_tpu.utils.faults import FaultSchedule, inject  # noqa: E402

VOCAB, SEQ, MAX_NEW = 64, 272, 256


def main() -> int:
    seed = int(os.environ.get("FAULT_SEED", "0") or "0")
    rng = np.random.default_rng(seed)
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (SEQ,), "target": (SEQ,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    ws = tempfile.mkdtemp(prefix="chaos_streams_")
    mgr = CheckpointManager(ws, log_fn=lambda s: None)
    mgr.save(1, params, {"t": np.zeros(())}, health={"verdict": "ok"})
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=MAX_NEW,
                     batch_window_s=0.002, request_timeout_s=120.0,
                     cb="on", cb_slots=3, cb_block_len=16)
    fleet = EngineFleet.local(
        net, spec, 3, workspace=ws, params=params,
        router_spec=RouterSpec(probe_period_s=0.1, quarantine_after=5,
                               request_timeout_s=120.0, hedge="off"),
        log_fn=lambda s: None)
    fleet.start()
    kill_at = int(rng.integers(8, MAX_NEW // 2))
    rates = {"serve.resume": float(rng.uniform(0.0, 0.5)),
             "fleet.dispatch": float(rng.uniform(0.0, 0.05))}
    sched = FaultSchedule(rates=rates, seed=seed)
    results = []

    def client(k: int) -> None:
        prompt = [int(t) for t in rng.integers(1, VOCAB, 4)]
        seen, outcome = [], None
        try:
            for ev in fleet.generate_stream(prompt, max_new=MAX_NEW):
                if ev.get("done"):
                    outcome = ("done", ev)
                    break
                seen.append(int(ev["i"]))
                if k == 0 and len(seen) == kill_at:
                    sess = fleet.router.sessions.snapshot()
                    victim = sess["sessions"][0]["engine"]
                    fleet.router.handle_for(victim).kill()
        except Exception as e:  # noqa: BLE001 — a terminal error is OK
            outcome = ("error", repr(e))
        results.append((k, seen, outcome))

    with inject(sched):
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "CHAOS HANG: a stream is stuck"
    fleet.stop()
    for k, seen, outcome in sorted(results):
        assert outcome is not None, f"stream {k} vanished"
        assert seen == sorted(set(seen)), \
            f"stream {k} dup/garbled indices: {seen}"
        assert seen == list(range(len(seen))), \
            f"stream {k} gap before failure: {seen}"
        kind, detail = outcome
        if kind == "done" and "error" not in detail:
            assert len(detail.get("tokens", [])) >= len(seen), \
                f"stream {k} terminal lost tokens"
        print(f"stream {k}: {kind}, {len(seen)} tokens, "
              f"{'clean' if kind == 'done' else detail}")
    counters = {k: v
                for k, v in fleet.router.sessions.snapshot().items()
                if k != "sessions"}
    print(f"seed={seed} kill_at={kill_at} rates={rates} "
          f"sessions={counters}")
    print("CHAOS_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
