#!/usr/bin/env bash
# Closed-loop pipeline smoke (ISSUE 10 acceptance): the trainer and
# the serving fleet run concurrently against ONE workspace.  FAILS
# unless
#   * the clean loop canaries and promotes EVERY blessed checkpoint in
#     order, zero rollbacks, blessed-to-served lag single-digit
#     seconds on CPU;
#   * under injected kill/corrupt/diverge faults zero client requests
#     fail, no response is ever served from below the promoted step or
#     from a non-blessed step, and the loop still drains;
#   * a REAL trainer process SIGKILLed mid-run (then restarted with
#     --resume) is invisible to traffic, and a DIVERGED or corrupted
#     checkpoint injected into the live workspace is contained at the
#     canary (rollback / refusal) with the fleet pinned.
# Writes BENCH_pr10.json.
#
# Usage: scripts/pipeline_smoke.sh       (CPU-only, no data, ~5 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — two in-process closed loops (clean +
# seeded kill/corrupt/diverge).  bench_pipeline_smoke raises (and this
# script fails) unless every acceptance bullet holds.
python bench.py --pipeline-smoke --out BENCH_pr10.json

# the recorded artifact must actually carry the numbers, not nulls
python - <<'EOF'
import json
with open("BENCH_pr10.json") as f:
    d = json.loads(f.read())
assert isinstance(d.get("value"), (int, float)) and d["value"] < 10, d
c, ft = d["clean"], d["faulted"]
assert c["promoted_sequence"] == [6, 12, 18, 24], c
assert c["rollbacks"] == 0 and c["client_failures"] == 0, c
assert ft["client_failures"] == 0 and ft["refusals"] >= 1, ft
assert ft["served_step"] == ft["blessed_step"] == 24, ft
assert sorted(ft["supervisor_failures"]) == \
    ["divergence", "preemption"], ft
print(f"BENCH_pr10.json ok: promote_lag_max={d['value']}s, clean "
      f"{c['promotions']}p/{c['rollbacks']}r, faulted "
      f"{ft['promotions']}p/{ft['refusals']}ref with "
      f"{ft['supervisor_failures']} absorbed")
EOF
echo "PIPELINE BENCH PASS: every blessed checkpoint reached traffic,"
echo "  kill/corrupt/diverge injection cost zero client failures"

# Leg 2: the CLI surface — `pipeline --smoke` with a trainer
# preemption AND a NaN'd gradient window injected mid-pipeline; the
# subcommand's own gates (zero failed requests, loop drained) decide.
WS=$(mktemp -d -t pipeline_smoke_cli_XXXX)
trap 'rm -rf "$WS"' EXIT
python -m singa_tpu.main pipeline \
    -model_conf examples/transformer/lm_tiny.conf \
    --workspace "$WS" --synthetic --smoke 40 \
    --fault_spec 'step.train@20:preempt,step.grad@30:nan' \
    --serve_spec 'buckets=2x8,max_new_tokens=4,batch_window_s=0.005' \
    --rollout_spec 'poll_s=0.2,window_s=0.5,min_requests=2' \
    | grep -E '"lag_steps": 0' > /dev/null || {
        echo "PIPELINE SMOKE CLI LEG FAILED"; exit 1; }
echo "PIPELINE SMOKE CLI PASS"

# Leg 3: a REAL trainer process SIGKILLed mid-pipeline — not a
# simulated preemption — plus a DIVERGED verdict and a corrupted
# snapshot injected into the live workspace while the fleet serves.
python - <<'EOF'
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

CONF = "examples/transformer/lm_tiny.conf"
STEPS = 240                      # cadence 8 -> final blessed step 240
ws = tempfile.mkdtemp(prefix="pipeline_kill_")


def spawn(resume=False):
    cmd = [sys.executable, "-m", "singa_tpu.main", "-model_conf", CONF,
           "--synthetic", "--workspace", ws, "--steps", str(STEPS)]
    if resume:
        cmd.append("--resume")
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


import jax

from singa_tpu.config import load_model_config
from singa_tpu.core.trainer import Trainer
from singa_tpu.data import discover_input_shapes
from singa_tpu.serve import EngineFleet, RolloutSpec, ServeSpec
from singa_tpu.utils.checkpoint import CheckpointManager

model = load_model_config(CONF)
shapes = discover_input_shapes(model, force_synthetic=True)
tr = Trainer(model, shapes, log_fn=lambda s: None)
net = tr.test_net or tr.train_net
fleet = EngineFleet.local(
    net, ServeSpec.parse("buckets=2x8,max_new_tokens=4,"
                         "batch_window_s=0.002"),
    2, workspace=ws, params=net.init_params(jax.random.PRNGKey(0)),
    rollout_spec=RolloutSpec(poll_s=0.1, window_s=0.25,
                             min_requests=1),
    log_fn=lambda s: None)
fleet.start()

rng = np.random.default_rng(0)
failures = 0


def request():
    """One client request; returns the served step.  Every response
    must come from the promoted step or newer (the canary), never
    below."""
    global failures
    pinned = fleet.rollout.pinned_step
    try:
        out = fleet.generate(
            rng.integers(1, 64, int(rng.integers(1, 7))).astype("int32"))
    except Exception:  # noqa: BLE001 — counted, asserted zero
        failures += 1
        return None
    assert out["step"] >= pinned, (out["step"], pinned)
    return out["step"]


reader = CheckpointManager(ws, log_fn=lambda s: None)
proc = spawn()
try:
    # traffic until the first checkpoint lands on disk (step 8 of 240,
    # so the trainer is guaranteed mid-run), then SIGKILL it
    deadline = time.time() + 240
    while time.time() < deadline and not reader.fingerprint()[0]:
        request()
    assert reader.fingerprint()[0], "no checkpoint ever landed"
    assert proc.poll() is None, "trainer finished before the kill"
    proc.send_signal(signal.SIGKILL)       # a REAL process death
    proc.wait()
    pinned_at_kill = fleet.rollout.pinned_step
    for _ in range(30):                    # traffic must not notice
        request()
    assert fleet.rollout.pinned_step >= pinned_at_kill

    # restart with --resume: the loop picks up and drains to the end
    proc = spawn(resume=True)
    deadline = time.time() + 300
    while time.time() < deadline and fleet.rollout.pinned_step < STEPS:
        request()
    assert fleet.rollout.pinned_step == STEPS, \
        f"loop never drained: pinned {fleet.rollout.pinned_step}"
    proc.wait(timeout=60)

    # a DIVERGED verdict lands in the live workspace: contained at the
    # canary (rollback), fleet stays pinned
    mgr = CheckpointManager(ws, log_fn=lambda s: None)
    bad = net.init_params(jax.random.PRNGKey(1))
    rollbacks_before = fleet.rollout.rollbacks
    mgr.save(STEPS + 8, bad, {"t": np.zeros(())},
             health={"verdict": "diverged"})
    deadline = time.time() + 60
    max_on_bad = 0
    while (time.time() < deadline
           and fleet.rollout.rollbacks == rollbacks_before):
        request()
        on_bad = sum(1 for n in fleet.router.names()
                     if fleet.router.engine_step(n) == STEPS + 8)
        max_on_bad = max(max_on_bad, on_bad)
    assert fleet.rollout.rollbacks == rollbacks_before + 1, \
        "diverged save never rolled back"
    assert max_on_bad <= 1, f"{max_on_bad} engines on the diverged step"
    assert fleet.rollout.pinned_step == STEPS

    # a corrupted newest snapshot: refused at the canary reload
    refusals_before = fleet.rollout.refusals
    mgr.save(STEPS + 16, bad, {"t": np.zeros(())},
             health={"verdict": "ok"})
    stepdir = os.path.join(ws, "checkpoints", str(STEPS + 16))
    datafiles = [os.path.join(r, f)
                 for r, _, fs in os.walk(stepdir) for f in fs]
    biggest = max(datafiles, key=os.path.getsize)
    with open(biggest, "r+b") as fh:         # torn write: half the data
        fh.truncate(os.path.getsize(biggest) // 2)
    deadline = time.time() + 60
    while (time.time() < deadline
           and fleet.rollout.refusals == refusals_before):
        request()
    assert fleet.rollout.refusals > refusals_before, \
        "corrupt snapshot never refused"
    assert fleet.rollout.pinned_step == STEPS
    assert failures == 0, f"{failures} client-visible failures"
    print(f"subprocess pipeline ok: SIGKILL mid-run + resume drained "
          f"to step {STEPS}, diverged save rolled back "
          f"(max {max_on_bad} engine on it), corrupt snapshot "
          f"refused, 0 client failures")
finally:
    if proc.poll() is None:
        proc.kill()
    fleet.stop()
EOF
echo "PIPELINE SUBPROCESS PASS: real trainer SIGKILL + workspace"
echo "  corruption contained; serving never regressed, zero failures"
