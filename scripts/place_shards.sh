#!/usr/bin/env bash
# Place per-process shard folders onto their hosts.
#
# Successor of the reference's cluster ops glue (script/load_data.py's
# placement step + script/node.sh's ssh fan-out): after
#   python -m singa_tpu.tools.loader partition <shard_dir> <out_dir> \
#       <nworkers> [group_size] [--replicate]
# has produced <out_dir>/proc{i}/ folders, this pushes proc{i} to
# <remote_dir>/proc{i}/ on the i-th host of a hostfile (same format
# main.py consumes: one "host" or "host:port" per line, '#' comments
# and blank lines skipped, line i = process i — the port names the
# process, not the ssh target, so it is stripped for rsync; keeping
# the proc{i} suffix remotely means several processes on one host
# never collide).  Point each process at <remote_dir>/proc{i}.
#
# Usage: scripts/place_shards.sh <out_dir> <hostfile> <remote_dir> [run]
#   scripts/place_shards.sh data/parts hostfile /data/singa run
# Without the trailing "run" it prints the rsync commands (dry run) —
# the honest default for an ops script that mutates remote hosts.
set -euo pipefail

if [ $# -lt 3 ]; then
  echo "usage: $0 <out_dir> <hostfile> <remote_dir> [run]" >&2
  exit 1
fi
out_dir=$1; hostfile=$2; remote_dir=$3; mode=${4:-dry}

i=0
pids=()
hosts=()
# `|| [ -n "$host" ]` keeps a final line without a trailing newline
while read -r host _ || [ -n "${host:-}" ]; do
  case "${host:-}" in ''|'#'*) continue ;; esac
  src="$out_dir/proc$i"
  if [ ! -d "$src" ]; then
    echo "warning: $src missing (fewer partitions than hosts?)" >&2
    i=$((i + 1)); continue
  fi
  ssh_host=${host%%:*}
  cmd=(rsync -az --mkpath "$src/" "$ssh_host:$remote_dir/proc$i/")
  if [ "$mode" = run ]; then
    echo "+ ${cmd[*]}" >&2
    "${cmd[@]}" &
    pids+=($!); hosts+=("$host")
  else
    echo "${cmd[*]}"
  fi
  i=$((i + 1))
done < "$hostfile"

fail=0
for j in "${!pids[@]}"; do
  if ! wait "${pids[$j]}"; then
    echo "ERROR: placement to ${hosts[$j]} failed" >&2
    fail=1
  fi
done
exit $fail
