#!/usr/bin/env bash
# Traffic smoke (ISSUE 11 acceptance): the SLO-driven autoscaler under
# adversarial open-loop traffic, on CPU.  FAILS unless
#   * the 1-engine fleet GROWS under a flash crowd (scale_ups >= 1,
#     peak engines above the start) and SHRINKS back once quiet
#     (scale_downs >= 1, final below peak);
#   * p95 stays inside the SLO outside the spike (gated on the quiet
#     phase), with zero non-shed failures and zero harness drops;
#   * retiring the engine that holds a live slow-reader stream with
#     drain=True delivers every token and the done event first —
#     scale-down never drops an in-flight stream.
# Writes BENCH_pr11.json (per-phase offered/completed/shed +
# percentiles, autoscaler outcome counters, engine-count trajectory).
#
# Usage: scripts/traffic_smoke.sh        (CPU-only, no data, ~2 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — ramp -> flash crowd -> decay -> quiet over
# a growable in-process fleet.  bench_traffic_smoke raises (and this
# script fails) unless every acceptance bullet holds.
python bench.py --traffic-smoke --out BENCH_pr11.json

# the recorded artifact must actually carry the numbers, not nulls
python - <<'EOF'
import json
with open("BENCH_pr11.json") as f:
    d = json.loads(f.read())
for k in ("value", "offered", "completed", "shed"):
    assert isinstance(d.get(k), (int, float)), \
        f"BENCH_pr11.json: {k} missing/null: {d.get(k)}"
assert d["failed"] == 0, d
assert d["scale_ups"] >= 1 and d["scale_downs"] >= 1, d
assert d["engines_peak"] > 1 and d["engines_final"] < d["engines_peak"], d
assert d["value"] <= d["slo_p95_ms"], d
assert d["stream_drained"] is True, d
print(f"BENCH_pr11.json ok: quiet p95={d['value']}ms "
      f"(SLO {d['slo_p95_ms']}ms), engines 1->{d['engines_peak']}->"
      f"{d['engines_final']}, {d['scale_ups']} up/{d['scale_downs']} "
      f"down, shed={d['shed']}/{d['offered']}, failed=0")
EOF
echo "TRAFFIC BENCH PASS: flash crowd answered with capacity, quiet"
echo "  answered with drain-safe scale-down, zero non-shed failures"

# Leg 2: the regression suite — control law, drain semantics,
# canary-abort-on-retire, open-loop property, all on stub handles.
python -m pytest tests/test_autoscale.py -q -m traffic \
    -p no:cacheprovider

# Leg 3: the CLI surface — `serve --fleet 1` with an --autoscale_spec
# publishes the autoscaler snapshot in the smoke summary.
python -m singa_tpu.main serve -model_conf examples/transformer/lm.conf \
    --fleet 1 --smoke 6 \
    --serve_spec 'buckets=2x8,max_new_tokens=4,batch_window_s=0.005' \
    --autoscale_spec 'min_engines=1,max_engines=2,tick_s=0.1' \
    | grep -E '"autoscale"' > /dev/null || {
        echo "TRAFFIC SMOKE CLI LEG FAILED"; exit 1; }
echo "TRAFFIC SMOKE CLI PASS"

# Leg 4: the report — every BENCH_pr*.json lands in one table and the
# new artifact is in it.
python tools/bench_report.py | grep -E 'BENCH_pr11' > /dev/null || {
    echo "BENCH REPORT LEG FAILED"; exit 1; }
python tools/bench_report.py
echo "TRAFFIC SMOKE PASS"
