#!/usr/bin/env bash
# Tenant-isolation smoke (ISSUE 18 acceptance): two tenants share ONE
# engine and one tenant's flash crowd must stay that tenant's
# problem, on CPU.  FAILS unless
#   * tenant B's flash-phase p95 stays within 1.2x its quiet-phase
#     p95 and B completes 100% of its offered requests with zero
#     sheds while tenant A floods at >= 5x B's rate;
#   * A's overflow is shed honestly (Overloaded) with a per-tenant
#     ESCALATING Retry-After across consecutive sheds;
#   * the per-tenant retry-budget floor holds: A draining its budget
#     and the shared bucket dry leaves B still able to spend from
#     its guaranteed floor;
#   * zero non-shed failures and zero harness drops.
# Writes BENCH_pr18.json (per-phase per-tenant offered/completed/
# shed/p95, the Retry-After ladder, the budget-floor outcome).
#
# Usage: scripts/tenant_smoke.sh        (CPU-only, no data, ~1 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: the bench smoke — quiet -> tenant-A flash crowd over a
# quota-partitioned 1-engine fleet.  bench_tenant_smoke raises (and
# this script fails) unless every acceptance bullet holds.
python bench.py --tenant-smoke --out BENCH_pr18.json

# the recorded artifact must actually carry the numbers, not nulls
python - <<'EOF'
import json
with open("BENCH_pr18.json") as f:
    d = json.loads(f.read())
assert isinstance(d.get("value"), (int, float)), d.get("value")
assert 0.0 < d["value"] <= 1.2, d["value"]
fb = d["flash"]["by_tenant"]["b"]
fa = d["flash"]["by_tenant"]["a"]
assert fb["completed"] == fb["offered"] and fb["shed"] == 0, fb
assert fa["shed"] >= 1, fa
assert d["retry_escalation_ratio"] >= 1.5, d["retry_escalation_ratio"]
g = d["gates"]
assert g["budget_floor_b_admitted"]["pass"], g
assert g["budget_floor_a_exhausted"]["pass"], g
print(f"BENCH_pr18.json ok: B p95 ratio={d['value']} (bound 1.2), "
      f"B {fb['completed']}/{fb['offered']} completed, "
      f"A shed={fa['shed']}/{fa['offered']}, "
      f"retry escalation x{d['retry_escalation_ratio']}")
EOF
echo "TENANT BENCH PASS: A's flash crowd stayed A's problem — B's"
echo "  p95 and completion untouched, budget floor held"

# Leg 2: the regression suite — registry grammar, quota enforcement,
# budget floors, (tenant, class) streaks, label-cardinality bounds,
# model-aware 404s, all on stubs.
python -m pytest tests/test_tenancy.py -q -m tenancy \
    -p no:cacheprovider

# Leg 3: the CLI surface — `serve --fleet 1` with a --tenant_spec
# publishes the tenancy envelopes and per-tenant counters in the
# smoke summary.
python -m singa_tpu.main serve -model_conf examples/transformer/lm.conf \
    --fleet 1 --smoke 4 \
    --serve_spec 'buckets=2x8,max_new_tokens=4,batch_window_s=0.005' \
    --tenant_spec 'a,queue_frac=0.25,budget_floor=4;b,queue_frac=0.5' \
    | grep -E '"tenancy"' > /dev/null || {
        echo "TENANT SMOKE CLI LEG FAILED"; exit 1; }
echo "TENANT SMOKE CLI PASS"

# Leg 4: the report — every BENCH_pr*.json lands in one table and the
# new artifact is in it.
python tools/bench_report.py | grep -E 'BENCH_pr18' > /dev/null || {
    echo "BENCH REPORT LEG FAILED"; exit 1; }
python tools/bench_report.py
echo "TENANT SMOKE PASS"
