#!/usr/bin/env bash
# Telemetry smoke (ISSUE 6 acceptance): a faulted supervised training
# run and a serving leg, each under --obs on / obs.session, must
# produce (1) a Chrome trace JSON whose spans cover supervisor /
# checkpoint / feeder / batcher / engine with matching correlation
# ids, (2) a JSONL event log carrying the injected fault's recovery
# events, and (3) on the serve leg a /metrics endpoint that parses as
# Prometheus text exposition and agrees with /stats.  The ISSUE 14
# leg proves DISTRIBUTED tracing: a subprocess worker's /trace ring
# merged with the router's buffer yields one trace id across both
# processes with zero orphan spans.  Finishes with the obs-overhead
# A/B gate (< 3%) -> BENCH_pr6.json.
#
# Usage: scripts/obs_smoke.sh        (CPU-only, no data, ~2 min)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Leg 1: supervised training with a mid-run preemption, spans + events
# asserted in-process (supervisor attempt/restore, checkpoint save /
# restore, per-dispatch chunk spans, feeder staging, attempt-N
# correlation flowing into the recovery).
python - <<'EOF'
import json
import tempfile

import numpy as np

from singa_tpu import obs
from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.supervisor import Supervisor
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils.faults import Backoff, FaultSchedule, inject

SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def cfg(steps=20, ckpt=5):
    return model_config_from_dict({
        "name": "obs-smoke", "train_steps": steps,
        "checkpoint_frequency": ckpt,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
             "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w", "init_method": "kUniformSqrtFanIn"},
                       {"name": "b"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip", "label"]}]}})


def data():
    return synthetic_image_batches(8, seed=7, stream_seed=111)


tmp = tempfile.mkdtemp(prefix="obs_smoke_")
trace_path = f"{tmp}/trace.json"
events_path = f"{tmp}/events.jsonl"

with obs.session(obs.ObsSpec(trace=trace_path, events=events_path)):
    # faulted supervised run: preempt at step 12, restore the step-10
    # snapshot on attempt 2 (unchunked so step.train visits == steps)
    tr = Trainer(cfg(), SHAPES, log_fn=lambda s: None, donate=False)
    sup = Supervisor(tr, f"{tmp}/ws", max_restarts=2,
                     backoff=Backoff(base=0.0, cap=0.0, jitter=0.0),
                     log=lambda s: None)
    with inject(FaultSchedule.parse("step.train@12:preempt", seed=0)):
        p, _, _ = sup.run(data, seed=0)
    for k in p:
        assert np.all(np.isfinite(np.asarray(p[k]))), k
    # a chunked + feeder run in the same session covers the feed spans
    tr2 = Trainer(cfg(steps=8, ckpt=0), SHAPES, log_fn=lambda s: None,
                  donate=False)
    p2, o2 = tr2.init(seed=0)
    tr2.run(p2, o2, data(), seed=0, scan_chunk=4, feeder=True)

trace = json.load(open(trace_path))
spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
names = {e["name"] for e in spans}
need = {"supervisor.attempt", "supervisor.restore", "ckpt.save",
        "ckpt.restore", "trainer.chunk", "feeder.stage", "feeder.pull",
        "feeder.wait"}
assert need <= names, f"missing spans: {need - names}"
corrs = {e["args"].get("corr") for e in spans}
assert {"attempt-1", "attempt-2"} <= corrs, corrs
# the recovery correlates: attempt-2's restore span carries its corr,
# and the nested ckpt.restore inherits it on the same thread
restores = [e for e in spans if e["name"] == "ckpt.restore"]
assert any(e["args"].get("corr") == "attempt-2" for e in restores), \
    [e["args"] for e in restores]
by_id = {e["args"]["span_id"]: e for e in spans}
for e in restores:
    parent = by_id[e["args"]["parent_id"]]
    assert parent["name"] == "supervisor.restore", parent["name"]

events = [json.loads(l) for l in open(events_path)]
kinds = [e["kind"] for e in events]
assert "supervisor.restart" in kinds, kinds
assert "supervisor.resumed" in kinds, kinds
restart = next(e for e in events if e["kind"] == "supervisor.restart")
assert restart["fail_kind"] == "preemption", restart
resumed = next(e for e in events if e["kind"] == "supervisor.resumed")
assert resumed["corr"] == "attempt-2" and resumed["step"] == 10, resumed
print("OBS TRAIN LEG PASS: trace spans", sorted(need),
      "with attempt-1/attempt-2 correlation; recovery events logged")
EOF

# Leg 2: the CLI surface — --obs on writes the default artifacts under
# <workspace>/obs/ during a faulted supervised run.
WS=$(mktemp -d -t obs_smoke_cli_XXXX)
trap 'rm -rf "$WS"' EXIT
python -m singa_tpu.main -model_conf examples/mnist/mlp.conf \
    --synthetic --steps 12 --workspace "$WS" \
    --max-restarts 2 --fault_spec "step.train@6:preempt" \
    --obs on > /dev/null
test -s "$WS/obs/trace.json" || { echo "CLI leg: no trace"; exit 1; }
test -s "$WS/obs/events.jsonl" || { echo "CLI leg: no events"; exit 1; }
python -c "import json; json.load(open('$WS/obs/trace.json'))"
grep -q '"kind": "supervisor.restart"' "$WS/obs/events.jsonl" || {
    echo "CLI leg: no restart event"; exit 1; }
echo "OBS CLI LEG PASS: default artifacts under workspace/obs/"

# Leg 3: serving — request->batch->engine correlation in the trace,
# /metrics parses as Prometheus text and agrees with /stats.
python - <<'EOF'
import json
import tempfile
import urllib.request

import jax
import numpy as np

from singa_tpu import obs
from singa_tpu.core.net import build_net
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.serve import InferenceEngine, InferenceServer, ServeSpec

cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=32,
                     num_heads=4, head_dim=8, seq_len=16, batchsize=2)
net = build_net(cfg, "kTest", {"data": {"input": (16,), "target": (16,)}})
params = net.init_params(jax.random.PRNGKey(0))
spec = ServeSpec(buckets=((2, 6),), max_new_tokens=3,
                 batch_window_s=0.005, request_timeout_s=20.0)

tmp = tempfile.mkdtemp(prefix="obs_smoke_serve_")
trace_path = f"{tmp}/trace.json"
with obs.session(obs.ObsSpec(trace=trace_path)):
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, port=0, log_fn=lambda s: None)
    server.start()
    try:
        for plen in (2, 4, 6):
            server.generate(np.arange(1, 1 + plen, dtype=np.int32))
        host, port = server.address
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            metrics = obs.parse_prometheus(r.read().decode())
        with urllib.request.urlopen(
                f"http://{host}:{port}/stats", timeout=30) as r:
            stats = json.load(r)
    finally:
        server.stop()

for k in ("submitted", "completed", "failed", "shed", "batches",
          "compiles"):
    assert metrics[f"singa_serve_{k}_total"] == stats[k], \
        (k, metrics.get(f"singa_serve_{k}_total"), stats[k])
assert stats["completed"] == 3, stats

trace = json.load(open(trace_path))
spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
names = {e["name"] for e in spans}
need = {"batcher.admit", "batcher.dispatch", "engine.compile",
        "engine.run_batch"}
assert need <= names, f"missing spans: {need - names}"
# correlation: every admitted req-N reappears in some dispatch span's
# member list, and engine.run_batch inherits the batch-M corr
admits = {e["args"]["corr"] for e in spans
          if e["name"] == "batcher.admit"}
dispatched = set()
for e in spans:
    if e["name"] == "batcher.dispatch":
        assert e["args"]["corr"].startswith("batch-"), e["args"]
        dispatched.update(
            json.loads(e["args"]["reqs"].replace("'", '"'))
            if isinstance(e["args"]["reqs"], str) else e["args"]["reqs"])
assert admits <= dispatched, (admits, dispatched)
runs = [e for e in spans if e["name"] == "engine.run_batch"]
assert runs and all(e["args"]["corr"].startswith("batch-")
                    for e in runs), [e["args"] for e in runs]
print("OBS SERVE LEG PASS: req->batch->engine correlated;",
      "/metrics == /stats on", sorted(metrics)[:3], "...")
EOF

# Leg 4 (ISSUE 14): distributed tracing across REAL process
# boundaries — a subprocess `serve --pinned` worker with its span
# ring on GET /trace, an in-process router session sending one
# request with the X-Trace-Id/X-Parent-Span pair, and obs.collect
# merging both buffers into ONE trace: worker spans must carry the
# router's trace id, with zero orphan spans, and the text timeline
# tool must render the merged file.
python - <<'EOF'
import json
import subprocess
import sys
import tempfile
import time
import urllib.request

PORT = 18491
SPEC = ("buckets=2x128,max_new_tokens=16,batch_window_s=0.005,"
        "cb=on,cb_slots=2,cb_block_len=16")
tmp = tempfile.mkdtemp(prefix="obs_smoke_dist_")

proc = subprocess.Popen(
    [sys.executable, "-m", "singa_tpu.main", "serve",
     "-model_conf", "examples/transformer/lm.conf",
     "--pinned", "--port", str(PORT), "--serve_spec", SPEC,
     "--workspace", tmp, "--obs", "on",
     "--obs_spec", "trace_ring=65536,process=worker-0"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
try:
    deadline = time.time() + 300
    while True:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{PORT}/healthz", timeout=2)
            break
        except Exception:
            if time.time() > deadline:
                raise RuntimeError("worker never came up")
            time.sleep(0.25)

    from singa_tpu import obs
    from singa_tpu.obs import collect
    from singa_tpu.serve import EngineFleet, RouterSpec

    with obs.session(obs.ObsSpec(process="router",
                                 trace_ring=65536)):
        fleet = EngineFleet.adopt(
            [f"http://127.0.0.1:{PORT}"],
            router_spec=RouterSpec(probe_period_s=0.1,
                                   quarantine_after=2,
                                   request_timeout_s=120.0,
                                   hedge="off"),
            log_fn=lambda s: None)
        fleet.start()
        out = fleet.generate([5, 7, 9, 11], timeout=120.0)
        assert out.get("tokens"), out
        row = fleet.router.requests.snapshot()["recent"][-1]
        trace_id = row["trace"]
        assert trace_id, row
        router_buf = obs.trace_dump()
        fleet.stop()

    worker_buf = collect.fetch_trace(f"http://127.0.0.1:{PORT}")
    merged = collect.merge([router_buf, worker_buf])
    spans = collect.spans_of(merged, trace_id)
    pids = {e["pid"] for e in spans}
    assert len(pids) >= 2, \
        f"trace {trace_id} did not cross the process boundary: {pids}"
    procs = set(merged.get("processes", {}).values())
    assert {"router", "worker-0"} <= procs, procs
    names = {e["name"] for e in spans}
    assert "router.dispatch" in names and "serve.request" in names, \
        names
    bad = collect.orphans(merged, trace_id)
    assert not bad, f"orphan spans: {[e['name'] for e in bad]}"

    merged_path = f"{tmp}/merged.json"
    with open(merged_path, "w") as f:
        json.dump(merged, f)
    txt = subprocess.run(
        [sys.executable, "tools/trace_timeline.py", merged_path,
         "--trace", trace_id],
        capture_output=True, text=True, timeout=60)
    assert txt.returncode == 0 and "critical path" in txt.stdout, \
        txt.stdout + txt.stderr
    print(f"OBS DIST LEG PASS: trace {trace_id} spans "
          f"{sorted(procs)} with zero orphans "
          f"({len(spans)} span(s) merged)")
finally:
    proc.kill()
    proc.wait(30)
EOF

# Leg 5: the overhead gate — --obs on must cost < 3% wall time on the
# chunked LeNet loop (bench_obs_overhead raises nothing; the JSON
# carries the verdict we assert here).
python bench.py --obs-overhead --out BENCH_pr6.json > /dev/null
python - <<'EOF'
import json
with open("BENCH_pr6.json") as f:
    d = json.load(f)
assert isinstance(d["value"], (int, float)), d
assert d["passed"] and d["value"] < d["gate"], \
    f"obs overhead {d['value']} >= gate {d['gate']}: {d}"
print(f"BENCH_pr6.json ok: obs overhead {d['value']*100:.2f}% "
      f"(gate {d['gate']*100:.0f}%), "
      f"off={d['wall_obs_off_s']}s on={d['wall_obs_on_s']}s")
EOF
# Leg 6 (ISSUE 15): the performance observatory end to end — exactly
# 2 cb compiles at warmup and 0 after under mixed load (the
# recompile-anomaly counter stays 0), readiness timers and the HBM
# watermark exported in /metrics, CostWatch harvesting adds 0
# compiles, observatory overhead under the same 3% bar, and the
# bench-trajectory report rendering every BENCH_pr*.json.
python bench.py --perf-smoke --out BENCH_pr15.json > /dev/null
python - <<'EOF'
import json
with open("BENCH_pr15.json") as f:
    d = json.load(f)
bad = {k: g for k, g in d["gates"].items() if not g["pass"]}
assert not bad, f"perf smoke gates failed: {bad}"
print(f"BENCH_pr15.json ok: {len(d['gates'])} gates pass "
      f"(post-warmup compiles {d['value']}, "
      f"restart-to-serving {d['restart_to_serving_s']}s, "
      f"watermark {d['hbm_watermark_bytes']}B, "
      f"obs overhead {d['obs_overhead']*100:.2f}%)")
EOF
python tools/bench_report.py --trajectory > /dev/null

echo "OBS SMOKE PASS: traces + events + /metrics artifacts verified,"
echo "  telemetry overhead under the 3% gate, perf observatory gated"
