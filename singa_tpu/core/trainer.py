"""Trainer: the TPU-native Worker (reference src/worker/worker.cc).

The reference Worker spawns Executor threads that walk the layer DAG,
block on bridges/param versions, and push gradients at a ZMQ parameter
server.  Here the entire TrainOneBatch (worker.cc:187-316) — forward,
backward, and updater — is ONE jitted function; data parallelism is a
mesh sharding over the batch dim with XLA inserting the gradient psum
(see singa_tpu.parallel), so there is no parameter-server plane and no
CPU compute in the inner loop.

Cadence semantics preserved from ModelProto (model.proto:2-47):
  train_steps, test_steps, test_frequency/test_after_steps,
  validation_*, display_*; Performance metric averaging over the display
  interval (worker.cc:350-386); per-phase wall-time report in the style
  of TimerInfo (worker.h:91-114).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import obs
from ..obs import perf
from ..config.schema import ModelConfig
from ..utils import faults
from .net import NeuralNet, build_net
from .updater import Updater, make_updater


@dataclass
class Performance:
    """Metric aggregation over an interval (worker.cc:350-386)."""
    totals: Dict[str, float] = field(default_factory=dict)
    counter: int = 0

    def update(self, metrics: Dict[str, jnp.ndarray]) -> None:
        for k, v in metrics.items():
            self.totals[k] = self.totals.get(k, 0.0) + float(v)
        self.counter += 1

    def to_string(self) -> str:
        n = max(self.counter, 1)
        return ", ".join(f"{k} : {v / n:.6f}"
                         for k, v in sorted(self.totals.items()))

    def averages(self) -> Dict[str, float]:
        n = max(self.counter, 1)
        return {k: v / n for k, v in self.totals.items()}

    def reset(self) -> None:
        self.totals.clear()
        self.counter = 0


@dataclass
class TimerInfo:
    """Per-phase wall-time accumulator (worker.h:91-114).  Host phases:
    `wait` (blocked on the batch source / DeviceFeeder), `stage` (stack
    + device_put — ON the critical path in the synchronous loop, a
    producer-thread measurement that OVERLAPS `train` when the feeder
    is active, so wait+stage+train can exceed wall time there — see
    docs/PERFORMANCE.md), `train` (dispatch + device sync).  The
    device-side fwd/bwd/update split the reference timed around each
    phase call is one fused XLA program here, so it comes from a
    one-shot profiler trace (Trainer.profile_phases) and rides along as
    `phase_shares`."""
    times: Dict[str, float] = field(default_factory=dict)
    steps: int = 0
    phase_shares: Optional[Dict[str, float]] = None

    def add(self, phase: str, seconds: float) -> None:
        self.times[phase] = self.times.get(phase, 0.0) + seconds

    def to_string(self) -> str:
        total = sum(self.times.values()) or 1.0
        parts = [f"{k}: {v / max(self.steps, 1) * 1e3:.2f}ms "
                 f"({100 * v / total:.0f}%)"
                 for k, v in self.times.items()]
        out = "Time per step — " + ", ".join(parts)
        if self.phase_shares:
            shares = dict(self.phase_shares)
            cov = shares.pop("coverage", None)
            out += " [device: " + ", ".join(
                f"{k} {100 * v:.0f}%" for k, v in shares.items())
            if cov is not None:
                # fusion blur can swallow a phase (classify_phase);
                # the coverage qualifier keeps "update 0%" honest
                out += f" — {100 * cov:.0f}% of device time attributed"
            out += "]"
        return out

    def reset(self) -> None:
        self.times.clear()
        self.steps = 0

    def register_into(self, registry,
                      prefix: str = "singa_train") -> None:
        """Register this timer's phase totals into an
        `obs.MetricsRegistry` as a pull-time collector — additive; the
        timer's own API and report are untouched."""
        from ..obs.metrics import Sample

        def collect():
            out = [Sample(f"{prefix}_steps_total", "counter",
                          "training steps timed", float(self.steps))]
            for phase, secs in sorted(self.times.items()):
                out.append(Sample(
                    f"{prefix}_phase_{phase}_seconds_total", "counter",
                    f"cumulative host seconds in the {phase!r} phase",
                    secs))
            return out

        registry.register_collector(collect)


class Trainer:
    """Single-controller training driver.

    `data_factory(phase, net)` must return an iterator of batch dicts
    matching the net's data layers (see singa_tpu.data.pipeline).
    """

    def __init__(self, model_cfg: ModelConfig,
                 input_shapes: Dict[str, Dict[str, tuple]],
                 log_fn: Optional[Callable[[str], None]] = None,
                 donate: bool = True, mesh=None, n_micro: int = 0,
                 ngroups: int = 1, health=None):
        """`mesh` + layers carrying locationid stage marks → the staged
        region runs pipelined over the mesh's "pipe" axis (see
        parallel.pipeline_net); `n_micro` sets the GPipe microbatch
        count (default 2·pipe — ClusterProto.pipeline_microbatches maps
        here from main.py).

        When UpdaterProto's consistency knobs request the async tier
        (param_type Elastic with moving_rate > 0, or RandomSync —
        parallel.elastic.async_active), `run` exchanges params with a
        center copy at sync_frequency after warmup_steps, exactly the
        reference worker's cadence (worker.cc:44-55); `ngroups` scales
        Elastic's alpha = moving_rate/ngroups (param_manager.cc:15).
        Multi-replica groups run through parallel.elastic.ReplicaSet.

        `health` (a utils.health.HealthMonitor) arms the numeric-health
        sentinel: the compiled train step gains device-side probes
        (grad/param norms, update ratio) that ride the deferred metrics
        ring, the ring drain classifies each step, fatal verdicts raise
        a structured NumericDivergence, and checkpoint saves carry (and
        are gated on) the window's health verdict.  None (the default)
        compiles exactly the pre-health step program."""
        self.cfg = model_cfg
        # default: the structured component logger (obs.log satellite)
        # — human-readable "[trainer] ..." lines, warning+ mirrored to
        # the event log when a session is live.  A caller-provided
        # log_fn (tests, serve_main) is used verbatim as before.
        self.log = log_fn if log_fn is not None \
            else obs.get_logger("trainer")
        self.mesh = mesh
        self.health = health
        self._donate = donate
        self.compute_dtype = (jnp.bfloat16
                              if model_cfg.precision == "bfloat16" else None)
        self.train_net = build_net(model_cfg, "kTrain", input_shapes)
        self.test_net = self._maybe_net("kTest", input_shapes)
        self.val_net = self._maybe_net("kValidation", input_shapes)
        # sequence-parallel nets shard token dims over "seq" too —
        # input placement (_batch_place/_chunk_place) must match
        self._uses_sp = any(
            l.attention_param and l.attention_param.seq_parallel != "none"
            for l in (model_cfg.neuralnet.layer
                      if model_cfg.neuralnet else []))
        self.updater = make_updater(model_cfg.updater)
        self.multipliers = self.train_net.multipliers()
        self._pipeline_nets = self._maybe_pipeline(n_micro)
        from ..parallel.elastic import ElasticController, async_active
        self.elastic = (ElasticController(model_cfg.updater, ngroups,
                                          log_fn=self.log)
                        if async_active(model_cfg.updater) else None)
        self._build_steps(donate)
        # AOT executables from `compiled_scan`, keyed by geometry —
        # one compile serves HLO text, cost harvesting, AND execution
        self._aot_cache: Dict[tuple, Any] = {}
        self.perf = Performance()
        self.timer = TimerInfo()
        # post-save publication hook (step, verdict) — the closed-loop
        # pipeline's train→serve seam (core/pipeline.py wires it).
        # Fires AFTER a snapshot is durably on disk with its health
        # verdict recorded; the cadence path drains the metrics ring
        # before every save, so drain-before-publish holds for free.
        # Observer semantics: a raising hook is logged, never a step
        # failure.
        self.on_checkpoint: Optional[Callable[[int, Optional[str]],
                                              None]] = None
        for nm, freq, steps in (
                ("test", model_cfg.test_frequency, model_cfg.test_steps),
                ("validation", model_cfg.validation_frequency,
                 model_cfg.validation_steps)):
            if freq > 0 and steps <= 0:
                self.log(f"warning: {nm}_frequency is set but "
                         f"{nm}_steps is 0 — no {nm} net is built and "
                         f"{nm} evaluation will not run (the reference "
                         f"gates eval nets on the step count, "
                         f"worker.cc:16-27)")

    def _maybe_pipeline(self, n_micro: int) -> Dict[int, Any]:
        """{id(net): PipelineNet} when the config marks stages AND the
        mesh has a pipe axis > 1; {} otherwise (locationid marks are
        inert on a flat mesh, matching the reference running a
        location-annotated net on a single worker)."""
        mesh = self.mesh
        has_pipe = (mesh is not None and "pipe" in getattr(mesh, "shape", {})
                    and mesh.shape["pipe"] > 1)
        staged = any(l.locationid > 0
                     for l in self.cfg.neuralnet.layer)
        if not (has_pipe and staged):
            return {}
        from ..parallel.pipeline_net import (HeteroPipelineNet,
                                             NonUniformStages,
                                             PipelineNet)
        n_micro = n_micro or 2 * mesh.shape["pipe"]
        nets = {}
        for net in (self.train_net, self.test_net, self.val_net):
            if net is not None:
                try:
                    nets[id(net)] = PipelineNet(net, n_micro)
                except NonUniformStages as e:
                    # the reference pipelines arbitrary locationid
                    # layouts (neuralnet.cc:198-323); non-stackable
                    # stages take the switch-dispatch form
                    self.log(f"pipeline: stages not SPMD-stackable "
                             f"({e}); using HeteroPipelineNet")
                    nets[id(net)] = HeteroPipelineNet(net, n_micro)
        return nets

    def _net_apply(self, net):
        """net.apply, or the pipelined equivalent when configured."""
        pnet = self._pipeline_nets.get(id(net))
        return net.apply if pnet is None else pnet.apply

    def _maybe_net(self, phase: str, input_shapes) -> Optional[NeuralNet]:
        """Build the eval net for `phase`, or None when the phase is not
        configured.  Mirrors the reference Worker, which builds the
        test/validation nets only when their step counts are set
        (worker.cc:16-27: `if(model.test_steps()) SetupNeuralNet(kTest)`)
        — e.g. conv.conf's two same-named per-phase data layers exclude
        kTrain/kTest but not kValidation, so a kValidation build would
        see duplicate nodes; with validation unconfigured it is never
        attempted.  A phase whose filtered layers lack a data or loss
        layer is also legitimately absent, but a CONFIGURED phase that
        fails to build (typo'd srclayer, bad shapes) raises instead of
        silently disabling evaluation (round-1 review: the old bare
        `except Exception` swallowed real config errors)."""
        steps = (self.cfg.test_steps if phase == "kTest"
                 else self.cfg.validation_steps)
        if steps <= 0:
            return None
        from .layers import LAYER_REGISTRY
        cfgs = [l for l in self.cfg.neuralnet.layer if phase not in l.exclude]
        has_data = any(getattr(LAYER_REGISTRY.get(l.type), "is_data", False)
                       for l in cfgs)
        has_loss = any(getattr(LAYER_REGISTRY.get(l.type), "is_loss", False)
                       for l in cfgs)
        if not (has_data and has_loss):
            return None
        net = build_net(self.cfg, phase, input_shapes)
        return net if net._loss_layers() else None

    # -- compiled steps ----------------------------------------------------
    #: TPU compiler options for conv-family step programs.  The
    #: scoped-VMEM budget (default 16MB) caps XLA's fusion depth; 96MB
    #: measured 136ms -> 128ms on the AlexNet gate workload (bigger
    #: conv/LRN fusions stop splitting), 112MB another -0.5..-0.9ms
    #: (confirmed by two same-process A/Bs at different window sizes),
    #: 120MB slightly worse again, and 128MB tips into catastrophic
    #: spills (2.8s/step) — swept on a v5e chip (tools/mfu_ab.py;
    #: the working path is jit(compiler_options=...), which the axon
    #: compile helper forwards per-compile).  The transformer family
    #: REGRESSES under the raised budget (0.201 -> 0.179 MFU — it
    #: shrinks the VMEM left to the Pallas flash kernels), and
    #: LeNet-scale convs HANG the compile under it, so the option
    #: applies only to nets whose widest convolution has >= 96 filters
    #: (see _compiler_options).
    TPU_CONV_COMPILER_OPTIONS = {"xla_tpu_scoped_vmem_limit_kib": "114688"}
    #: Attention-family programs get a MODEST raise instead: the 16MB
    #: default OOMs the flash backward's scoped stack at batch >= 16
    #: ("Scoped allocation 16.54M > 16.00M"), while the full conv-sized
    #: raise regresses the transformer (r2: 0.201 -> 0.179 MFU, VMEM
    #: stolen from the Pallas kernels).  32MB measured: batch 16/32
    #: compile and run, MFU 0.444 (b8) -> 0.467 (b32) same session.
    TPU_ATTN_COMPILER_OPTIONS = {"xla_tpu_scoped_vmem_limit_kib": "32768"}

    def _compiler_options(self):
        from ..ops.attention import _on_tpu
        if not _on_tpu():
            return None
        # Escape hatch (VERDICT r2 item 9): ModelProto scoped_vmem
        # (auto|on|off) selects the policy; SINGA_TPU_SCOPED_VMEM env
        # overrides it, so a user whose net trips the auto heuristic
        # either way is never at the mercy of the filter-count proxy.
        mode = os.environ.get("SINGA_TPU_SCOPED_VMEM",
                              getattr(self.cfg, "scoped_vmem", "auto"))
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"SINGA_TPU_SCOPED_VMEM must be auto|on|off, got "
                f"{mode!r}")
        if mode == "off":
            return None
        # Budgets are per FAMILY: attention-family nets take the modest
        # raise (the conv-sized one regresses them — it starves the
        # Pallas flash kernels of VMEM), everything else the conv
        # budget.  "on" forces the family-sized budget even where
        # auto's heuristic would skip it (e.g. LeNet-scale convs, at
        # the documented risk of the compile hang); it never selects
        # the wrong family's budget.
        attn = any(l.cfg.type in ("kAttention", "kLMHeadLoss")
                   for l in self.train_net.layers.values())
        family = (self.TPU_ATTN_COMPILER_OPTIONS if attn
                  else self.TPU_CONV_COMPILER_OPTIONS)
        if mode == "on":
            return dict(family)
        # auto: attention nets always benefit; conv stacks only at
        # AlexNet scale — the raised budget hung the LeNet compile
        # outright (>9min vs 55s; the compiler's conv window search
        # appears to explode with the bigger fusion space on
        # small-channel convs), and small nets don't need it.
        if attn:
            return dict(family)
        widths = [l.num_filters for l in self.train_net.layers.values()
                  if l.cfg.type == "kConvolution"]
        if widths and max(widths) >= 96:
            return dict(family)
        return None

    def _build_steps(self, donate: bool) -> None:
        net, updater, mults = self.train_net, self.updater, self.multipliers
        mesh, cdtype = self.mesh, self.compute_dtype
        net_apply = self._net_apply(net)
        copts = self._compiler_options()
        # device-side numeric probes fuse into the step program only
        # when a monitor is armed — the default compiles the exact
        # pre-health program (and metrics dict)
        health_on = self.health is not None
        if health_on:
            from ..utils.health import health_probes
        # `poison` (None in every normal call — extra traced argument
        # only when a step.grad fault fires) scales the gradients: NaN
        # for the `nan` kind, SPIKE_SCALE for `spike` — the silent
        # numeric failures the health tier exists to catch
        poisoned = (lambda grads, pz: grads if pz is None else
                    jax.tree_util.tree_map(lambda g: g * pz, grads))

        def train_step(params, opt_state, batch, step, rng, poison=None):
            def loss_fn(p):
                loss, metrics, _ = net_apply(p, batch, rng=rng, train=True,
                                             mesh=mesh, compute_dtype=cdtype,
                                             step=step)
                return loss, metrics
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = poisoned(grads, poison)
            new_params, opt_state = updater.update(
                step, grads, params, opt_state, multipliers=mults)
            if health_on:
                metrics = {**metrics,
                           **health_probes(grads, params, new_params)}
            return new_params, opt_state, metrics

        donate_args = (0, 1) if donate else ()
        self.train_step = jax.jit(train_step, donate_argnums=donate_args,
                                  compiler_options=copts)

        def train_scan(params, opt_state, batches, start_step, rng, nsteps,
                       stacked=False, poison=None):
            """`nsteps` training steps in ONE compiled program (lax.scan).

            Removes the per-step host dispatch from the inner loop — the
            TPU analogue of the reference keeping its hot loop inside the
            Executor thread (worker.cc:98-106) instead of crossing a
            process boundary per batch.  With `stacked=True` every leaf
            of `batches` carries a leading `nsteps` axis that is scanned
            over (a fresh batch per step); with the default False,
            `batches` is a single batch reused every step.  `poison`
            (None normally; an (nsteps,) grad-scale vector when a
            step.grad fault fires inside the chunk) is scanned over
            alongside the steps.  Returns stacked per-step metrics.
            """
            def body(carry, xs):
                p, o = carry
                step, batch, pz = xs
                if batch is None:
                    batch = batches
                step_rng = jax.random.fold_in(rng, step)

                def loss_fn(pp):
                    loss, metrics, _ = net_apply(
                        pp, batch, rng=step_rng, train=True, mesh=mesh,
                        compute_dtype=cdtype, step=step)
                    return loss, metrics
                (_, metrics), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(p)
                grads = poisoned(grads, pz)
                new_p, o = updater.update(step, grads, p, o,
                                          multipliers=mults)
                if health_on:
                    metrics = {**metrics,
                               **health_probes(grads, p, new_p)}
                return (new_p, o), metrics

            steps = start_step + jnp.arange(nsteps)
            if stacked:
                bad = [x.shape for x in jax.tree_util.tree_leaves(batches)
                       if getattr(x, "ndim", 0) < 1 or x.shape[0] != nsteps]
                if bad:
                    raise ValueError(
                        f"stacked=True needs a leading {nsteps}-axis on "
                        f"every batch leaf; got shapes {bad}")
            xs = (steps, batches if stacked else None, poison)
            # SINGA_TPU_SCAN_UNROLL replicates the step body in the
            # compiled loop (lax.scan unroll), trading compile time and
            # program size for fewer loop-iteration boundaries
            unroll = int(os.environ.get("SINGA_TPU_SCAN_UNROLL", "1"))
            (params, opt_state), metrics = jax.lax.scan(
                body, (params, opt_state), xs, length=nsteps,
                unroll=max(1, unroll))
            return params, opt_state, metrics

        self.train_steps = jax.jit(train_scan, static_argnums=(5, 6),
                                   donate_argnums=donate_args,
                                   compiler_options=copts)

        def make_eval(net):
            apply_fn = self._net_apply(net)

            def eval_step(params, batch):
                _, metrics, _ = apply_fn(params, batch, train=False,
                                         mesh=mesh, compute_dtype=cdtype)
                return metrics
            return jax.jit(eval_step, compiler_options=copts)

        self.test_step = make_eval(self.test_net) if self.test_net else None
        self.val_step = make_eval(self.val_net) if self.val_net else None

        def make_eval_scan(net):
            apply_fn = self._net_apply(net)

            def eval_scan(params, batches):
                """Stacked eval batches → stacked metrics in ONE
                compiled program — the eval counterpart of train_scan
                (a tunneled chip pays ~30ms per dispatch; a 100-step
                eval cadence was paying it 100 times)."""
                def body(carry, batch):
                    _, metrics, _ = apply_fn(params, batch, train=False,
                                             mesh=mesh,
                                             compute_dtype=cdtype)
                    return carry, metrics
                _, ms = jax.lax.scan(body, None, batches)
                return ms
            return jax.jit(eval_scan, compiler_options=copts)

        # evaluate() looks the fused variant up by the step_fn handed to
        # it, so external callers passing custom fns keep per-batch eval
        self._eval_scans = {}
        if self.test_step is not None:
            self._eval_scans[id(self.test_step)] = \
                make_eval_scan(self.test_net)
        if self.val_step is not None:
            self._eval_scans[id(self.val_step)] = \
                make_eval_scan(self.val_net)

        def debug_step(params, batch, step, rng):
            """Per-layer activations + param grads for DebugInfo
            (neuralnet.cc:350-378 prints data AND grad norms)."""
            def loss_fn(p):
                loss, _, outputs = net_apply(
                    p, batch, rng=rng, train=True, mesh=mesh,
                    compute_dtype=cdtype, step=step)
                return loss, outputs
            (_, outputs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return outputs, grads

        self.debug_step = (jax.jit(debug_step, compiler_options=copts)
                           if self.cfg.debug else None)

    def compiled_scan(self, params, opt_state, batches, start_step,
                      rng, nsteps: int, stacked: bool = False):
        """The AOT-compiled fused-scan executable for this geometry,
        compiled at most once and cached.  Every consumer of the
        compiled program — `profile_phases` (HLO text + traced runs),
        the convergence tool's pre-timing warmup, CostWatch harvesting
        — goes through here, so diagnostics never re-lower+recompile a
        program the trainer already owns.  Call the returned
        executable with the five traced args only (statics are baked
        in): `compiled(params, opt_state, batches, step, rng)`."""
        leaves = jax.tree_util.tree_leaves(batches)
        key = (int(nsteps), bool(stacked),
               tuple((tuple(x.shape), str(x.dtype)) for x in leaves))
        got = self._aot_cache.get(key)
        if got is not None:
            perf.lookup_hit("train_scan")
            return got
        with obs.span("trainer.compile", nsteps=nsteps,
                      stacked=stacked), \
             perf.compile_span("train_scan",
                               geometry=f"steps={nsteps},"
                                        f"stacked={stacked}",
                               scope="train"):
            got = self.train_steps.lower(
                params, opt_state, batches, start_step, rng, nsteps,
                stacked).compile()
        perf.harvest("train_scan", got)
        self._aot_cache[key] = got
        return got

    def profile_phases(self, params, opt_state, batch, step: int = 0,
                       rng=None, iters: int = 2,
                       outdir: Optional[str] = None) -> Dict[str, float]:
        """Measure the device-side fwd/bwd/update split of the train
        step (worker.h:91-114's tForward_/tBackward_/tSyncParam_ report)
        and pin it on `self.timer` for every subsequent TimerInfo line.

        One-shot cost: an AOT lower+compile of the scan step (for the
        HLO metadata) plus a short traced run.  Training state is not
        consumed — donated buffers are fed copies."""
        import tempfile

        from ..utils import profiler

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        outdir = outdir or tempfile.mkdtemp(prefix="singa_phase_prof_")
        # ONE compile serves both the HLO text and the traced runs —
        # executing through the cached AOT object (traced args only;
        # the statics are baked in) instead of re-dispatching the jit
        compiled = self.compiled_scan(params, opt_state, batch, step,
                                      rng, iters)
        txt = compiled.as_text()
        # the scan may donate params/opt_state — hand it copies
        cp = jax.tree_util.tree_map(jnp.copy, params)
        co = jax.tree_util.tree_map(jnp.copy, opt_state)
        p, _, _ = compiled(cp, co, batch, step, rng)
        profiler.hard_sync(p)   # execution path warm before the trace
        with profiler.trace(outdir):
            cp = jax.tree_util.tree_map(jnp.copy, params)
            co = jax.tree_util.tree_map(jnp.copy, opt_state)
            p, _, _ = compiled(cp, co, batch, step, rng)
            profiler.hard_sync(p)
        shares = profiler.phase_shares(outdir, txt)
        self.timer.phase_shares = shares
        return shares

    # -- init --------------------------------------------------------------
    def init(self, seed: int = 0):
        rng = jax.random.PRNGKey(seed)
        params = self.train_net.init_params(rng)
        opt_state = self.updater.init(params)
        # MemoryWatch analytic components — on backends with no
        # memory_stats() (CPU) these ARE the HBM model
        perf.set_memory_tree("train_params", params, scope="train")
        perf.set_memory_tree("opt_state", opt_state, scope="train")
        return params, opt_state

    # -- input placement + feed pipeline knobs -----------------------------
    def _batch_place(self, batch):
        """Sharded device placement for ONE batch (batch dim 0): under
        a mesh the batch dim shards over "data" (token dims over "seq"
        for sequence-parallel nets); without a mesh the batch is left
        to the jitted step's own placement."""
        if self.mesh is None:
            return batch
        from ..parallel import (batch_shardings, seq_batch_shardings,
                                shard_batch)
        return shard_batch(self.mesh, batch,
                           shardings_fn=(seq_batch_shardings
                                         if self._uses_sp
                                         else batch_shardings))

    def _chunk_place(self, stacked):
        """Placement for a STACKED chunk (leading scan axis, batch at
        dim 1): sharded device_put under the mesh — the fix for
        jnp.stack landing chunks on the default device — or a plain
        async device_put without one (either way the transfer can
        overlap the previous chunk's scan)."""
        if self.mesh is None:
            return jax.device_put(stacked)
        from ..parallel import place_chunk
        return place_chunk(self.mesh, stacked,
                           seq_axis=("seq" if self._uses_sp else None))

    @staticmethod
    def _feeder_on(feeder: Optional[bool]) -> bool:
        """Overlapped feed is ON by default for chunked loops; an
        explicit argument wins, then SINGA_TPU_FEEDER=0/1."""
        if feeder is not None:
            return bool(feeder)
        return os.environ.get("SINGA_TPU_FEEDER", "1") != "0"

    @staticmethod
    def _feeder_depth(depth: int = 0) -> int:
        """Staged-chunks-ahead bound (argument, then
        SINGA_TPU_FEEDER_DEPTH, default 2 — docs/PERFORMANCE.md)."""
        if depth and depth > 0:
            return int(depth)
        try:
            return max(1, int(os.environ.get("SINGA_TPU_FEEDER_DEPTH",
                                             "2")))
        except ValueError:
            return 2

    def _chunk_plan(self, start_step: int, scan_chunk: int):
        """Deterministic (start, length) chunk descriptors covering
        [start_step, train_steps) with the SAME cadence cuts the run
        loop computes — so the DeviceFeeder stages ahead without ever
        pulling a batch the loop won't train on, and a Supervisor
        restart (new start_step, fast-forwarded iterator) replays the
        identical consumption.  Pure in step (cadence config +
        elastic.sync_now are stateless predicates), so producer-thread
        evaluation is safe."""
        step = start_step
        while step < self.cfg.train_steps:
            n = self._next_chunk_len(step, scan_chunk)
            yield step, n
            step += n

    # -- cadence helpers (worker.h:127-160 semantics) ----------------------
    def _now(self, step, freq, after) -> bool:
        return freq > 0 and step >= after and step % freq == 0

    def display_now(self, step):
        return self._now(step, self.cfg.display_frequency,
                         self.cfg.display_after_steps)

    def test_now(self, step):
        return self._now(step, self.cfg.test_frequency,
                         self.cfg.test_after_steps)

    def validate_now(self, step):
        return self._now(step, self.cfg.validation_frequency,
                         self.cfg.validation_after_steps)

    # -- loops -------------------------------------------------------------
    def evaluate(self, params, data_iter: Iterator, steps: int,
                 step_fn, scan_chunk: int = 25,
                 feeder: Optional[bool] = None) -> Dict[str, float]:
        """Average metrics over `steps` eval batches.  When `step_fn` is
        one of the trainer's own eval steps, full chunks of `scan_chunk`
        batches run as ONE fused lax.scan dispatch (same amortization as
        the train loop's scan_chunk), consuming pre-staged chunks from a
        DeviceFeeder (staging overlaps the previous chunk's eval scan);
        the remainder and custom step_fns dispatch per batch.  Chunks
        and single batches both land sharded under the trainer's mesh.
        `feeder=False` (or SINGA_TPU_FEEDER=0) stages inline instead."""
        perf = Performance()
        steps = max(steps, 1)
        scan_fn = getattr(self, "_eval_scans", {}).get(id(step_fn))
        done = 0
        chunk = min(steps, max(scan_chunk, 1))
        if scan_fn is not None and chunk > 1:
            def eat(ms):
                for i in range(chunk):
                    perf.update({k: v[i] for k, v in ms.items()})
            nchunks = steps // chunk
            if self._feeder_on(feeder) and nchunks > 0:
                from ..data.feed import DeviceFeeder
                fd = DeviceFeeder(
                    data_iter, ((i * chunk, chunk)
                                for i in range(nchunks)),
                    place=self._chunk_place,
                    depth=self._feeder_depth(), capacity=chunk)
                try:
                    for _ in range(nchunks):
                        eat(jax.device_get(
                            scan_fn(params, fd.get().batches)))
                finally:
                    # stops the staging thread only — the remainder
                    # below keeps reading the same (untouched) iterator
                    fd.close()
                done = nchunks * chunk
            else:
                from ..data.feed import ChunkStager
                stager = ChunkStager(self._chunk_place, capacity=chunk)
                while steps - done >= chunk:
                    batches = [next(data_iter) for _ in range(chunk)]
                    eat(jax.device_get(
                        scan_fn(params, stager.stage(batches))))
                    done += chunk
        for _ in range(steps - done):
            batch = self._batch_place(next(data_iter))
            perf.update(jax.device_get(step_fn(params, batch)))
        return perf.averages()

    def _next_chunk_len(self, step: int, scan_chunk: int) -> int:
        """Longest chunk [step, step+n) that crosses no test/validate/
        checkpoint boundary (those must run on the host between compiled
        chunks); display steps may fall inside a chunk because their
        metrics come back stacked."""
        n = min(scan_chunk, self.cfg.train_steps - step)

        def next_event(freq, after):
            # smallest multiple of freq that is > step and >= after
            if freq <= 0:
                return None
            m = (step // freq + 1) * freq
            if m < after:
                m = -(-after // freq) * freq
            return m

        if self.elastic is not None:
            # chunks may not run past a sync step: the center exchange
            # happens on the host after that step completes
            freq = self.cfg.updater.sync_frequency
            warm = self.cfg.updater.warmup_steps
            e = (warm if step < warm
                 else warm + ((step - warm) // freq + 1) * freq)
            if self.elastic.sync_now(step):
                e = step
            n = min(n, e - step + 1)
        for freq, after in ((self.cfg.test_frequency,
                             self.cfg.test_after_steps),
                            (self.cfg.validation_frequency,
                             self.cfg.validation_after_steps)):
            e = next_event(freq, after)
            if e is not None:
                n = min(n, e - step)
        f = self.cfg.checkpoint_frequency
        if f > 0:
            # saves fire after steps s with (s+1) % f == 0; a chunk may
            # end on such a step but not run past it
            s_ck = ((step + 1 + f - 1) // f) * f - 1
            n = min(n, s_ck - step + 1)
        return max(n, 1)

    def run(self, params, opt_state,
            train_iter: Iterator,
            test_iter_factory: Optional[Callable[[], Iterator]] = None,
            val_iter_factory: Optional[Callable[[], Iterator]] = None,
            start_step: int = 0, seed: int = 0,
            hooks: Optional[List[Callable[[int, Dict], None]]] = None,
            workspace: Optional[str] = None, scan_chunk: int = 0,
            feeder: Optional[bool] = None, feeder_depth: int = 0):
        """The Worker::Run loop (worker.cc:98-106).  With `workspace`,
        checkpoints {params, opt_state, step} at checkpoint_frequency and
        on completion (the resume path the reference left as a TODO,
        worker.cc:65-67).

        `scan_chunk > 1` runs up to that many steps per device dispatch
        via the fused lax.scan program (train_steps); cadence events
        (test/validate/checkpoint/display) still fire at exactly the
        reference steps because chunks are cut at their boundaries.
        By default the chunked loop is OVERLAPPED: a DeviceFeeder
        thread stages chunk k+1 (stack into reusable buffers + sharded
        device_put) while chunk k's scan runs, and per-chunk metrics
        stay on device in a small ring, drained only at display/eval/
        checkpoint boundaries — the host never blocks on data or
        metrics between chunks (docs/PERFORMANCE.md).  `feeder=False`
        (or SINGA_TPU_FEEDER=0) selects the synchronous fallback, which
        stages inline through the SAME sharded placement helper;
        `feeder_depth` (or SINGA_TPU_FEEDER_DEPTH) bounds how many
        chunks the feeder runs ahead.  Both paths produce bit-identical
        trajectories (tests/test_feed.py).

        Preemption safety (the failure-recovery story the reference
        lacks, SURVEY.md §5 — any process death hangs its job): while a
        checkpoint manager is active, SIGTERM/SIGINT trigger a final
        snapshot at the current step and a clean early return, so a
        preempted TPU job resumes from where it stopped instead of its
        last cadence checkpoint."""
        if self.cfg.alg == "kContrastiveDivergence":
            return self.run_cd(params, opt_state, train_iter,
                               test_iter_factory=test_iter_factory,
                               val_iter_factory=val_iter_factory,
                               hooks=hooks, scan_chunk=scan_chunk,
                               start_step=start_step, seed=seed,
                               workspace=workspace)
        ckpt, interrupted, old_handlers = self._ckpt_guard(workspace)
        rng = jax.random.PRNGKey(seed ^ 0x5eed)
        if self.elastic is not None:
            # center seeds lazily from the first post-warmup params
            # inside maybe_sync (worker.cc:50-55 pushes AFTER warmup)
            self.log(f"async consistency tier active: "
                     f"{self.cfg.updater.param_type} sync_frequency="
                     f"{self.cfg.updater.sync_frequency} warmup="
                     f"{self.cfg.updater.warmup_steps}")
        history: List[Dict[str, float]] = []
        step = start_step
        chunked = bool(scan_chunk and scan_chunk > 1)
        fd = stager = None
        if chunked and self._feeder_on(feeder):
            from ..data.feed import DeviceFeeder
            fd = DeviceFeeder(train_iter,
                              self._chunk_plan(start_step, scan_chunk),
                              place=self._chunk_place,
                              depth=self._feeder_depth(feeder_depth),
                              capacity=scan_chunk)
        elif chunked:
            from ..data.feed import ChunkStager
            stager = ChunkStager(self._chunk_place, capacity=scan_chunk)

        # Deferred metric drain: per-chunk metrics stay device-resident
        # in `pending` and are fetched in order only at boundaries.
        # With the feeder the ring holds depth+1 chunks — the drain's
        # device_get doubles as backpressure, bounding in-flight
        # dispatches (and their staged input buffers) instead of letting
        # the host race arbitrarily far ahead.  Without it the ring is 1
        # (the synchronous per-chunk fetch, exactly the old loop).
        ring = (self._feeder_depth(feeder_depth) + 1
                if fd is not None else 1)
        pending: List[tuple] = []
        staged_credit = [0.0]   # feeder stage_seconds already reported
        last_dbg = [None]       # newest single-batch view (debug/profile)

        def _drain():
            if not pending:
                return
            with obs.span("trainer.drain", chunks=len(pending)):
                _drain_chunks()

        def _drain_chunks():
            while pending:
                s0, n, md, stacked = pending.pop(0)
                tg = time.perf_counter()
                md = jax.device_get(md)   # device sync: train time
                self.timer.add("train", time.perf_counter() - tg)
                per_step = ([{k: v[i] for k, v in md.items()}
                             for i in range(n)] if stacked else [md])
                for i, m in enumerate(per_step):
                    s = s0 + i
                    if self.health is not None:
                        # classify as the ring drains — the probes rode
                        # the deferred metrics, so detection costs no
                        # extra host sync; a fatal verdict aborts the
                        # attempt BEFORE this step reaches hooks or a
                        # checkpoint (the save below drains first)
                        verdict = self.health.observe(s, m)
                        if verdict.status != "ok":
                            obs.emit_event(
                                "health.verdict", step=s,
                                status=verdict.status,
                                metric=verdict.metric,
                                value=(float(verdict.value)
                                       if verdict.value is not None
                                       else None),
                                fatal=verdict.fatal)
                        if verdict.fatal:
                            raise verdict.to_error()
                    self.perf.update(m)
                    if hooks:
                        for h in hooks:
                            self._call_hook(h, s, m)
                    if self.display_now(s):
                        if (self.timer.phase_shares is None
                                and (getattr(self, "phase_profile", False)
                                     or os.environ.get(
                                         "SINGA_TPU_PHASE_PROFILE") == "1")):
                            # one-shot device fwd/bwd/update attribution;
                            # never let a profiler hiccup kill training
                            try:
                                self.profile_phases(
                                    params, opt_state, last_dbg[0],
                                    step=s, rng=rng)
                            except Exception as e:  # pragma: no cover
                                self.timer.phase_shares = {}
                                self.log(f"warning: phase profile "
                                         f"failed: {e}")
                        self.log(f"step-{s}: {self.perf.to_string()}")
                        self.log(self.timer.to_string())
                        self.perf.reset()

        try:
            while step < self.cfg.train_steps:
                faults.maybe_fault("step.train")
                if interrupted:
                    _drain()   # hooks/logs for every trained step first
                    self.log(f"signal {interrupted[0]} received: checkpointing "
                             f"at step {step} and stopping")
                    self._save_checkpoint(ckpt, step, params, opt_state)
                    break
                if self.val_step and self.validate_now(step) and val_iter_factory:
                    _drain()
                    avg = self.evaluate(params, val_iter_factory(),
                                        self.cfg.validation_steps, self.val_step)
                    self.log(f"step-{step} validation: " + ", ".join(
                        f"{k} : {v:.6f}" for k, v in sorted(avg.items())))
                if self.test_step and self.test_now(step) and test_iter_factory:
                    _drain()
                    avg = self.evaluate(params, test_iter_factory(),
                                        self.cfg.test_steps, self.test_step)
                    self.log(f"step-{step} test: " + ", ".join(
                        f"{k} : {v:.6f}" for k, v in sorted(avg.items())))
                    history.append({"step": step, **avg})

                n = self._next_chunk_len(step, scan_chunk) if chunked else 1
                poison = self._grad_poison(n)
                t0 = time.perf_counter()
                if not chunked:
                    batch = next(train_iter)
                    t1 = time.perf_counter()
                    batch = self._batch_place(batch)
                    t2 = time.perf_counter()
                    with obs.span("trainer.chunk", start=step, steps=1):
                        params, opt_state, metrics = self.train_step(
                            params, opt_state, batch, step,
                            jax.random.fold_in(rng, step),
                            poison[0] if poison is not None else None)
                    t3 = time.perf_counter()
                    pending.append((step, 1, metrics, False))
                    last_dbg[0] = batch
                elif fd is not None:
                    with obs.span("feeder.wait", start=step):
                        # blocks only if staging is behind
                        chunk = fd.get()
                    t1 = time.perf_counter()
                    if chunk.start != step or chunk.length != n:
                        from ..data.feed import FeedError
                        raise FeedError(
                            f"feed plan diverged: staged chunk "
                            f"[{chunk.start}, +{chunk.length}) vs loop "
                            f"[{step}, +{n})")
                    t2 = t1
                    with obs.span("trainer.chunk", start=step, steps=n):
                        params, opt_state, metrics = self.train_steps(
                            params, opt_state, chunk.batches, step, rng,
                            n, True, poison)
                    t3 = time.perf_counter()
                    pending.append((step, n, metrics, True))
                    last_dbg[0] = jax.tree_util.tree_map(
                        lambda x: x[n - 1], chunk.batches)
                    # producer-side staging time since the last sample —
                    # real host work, but OFF the critical path
                    self.timer.add("stage",
                                   fd.stage_seconds - staged_credit[0])
                    staged_credit[0] = fd.stage_seconds
                else:
                    batches = [next(train_iter) for _ in range(n)]
                    t1 = time.perf_counter()
                    with obs.span("feeder.stage", start=step, steps=n):
                        stacked = stager.stage(batches)
                    t2 = time.perf_counter()
                    with obs.span("trainer.chunk", start=step, steps=n):
                        params, opt_state, metrics = self.train_steps(
                            params, opt_state, stacked, step, rng, n,
                            True, poison)
                    t3 = time.perf_counter()
                    pending.append((step, n, metrics, True))
                    last_dbg[0] = jax.tree_util.tree_map(
                        lambda x: x[n - 1], stacked)
                self.timer.add("wait", t1 - t0)
                if t2 > t1:
                    self.timer.add("stage", t2 - t1)
                self.timer.add("train", t3 - t2)
                self.timer.steps += n
                # first completed train dispatch: cold-start readiness
                # latch (first call wins; later chunks are no-ops)
                perf.mark_training_ready()
                perf.observe_step("train_scan", (t3 - t2) / max(n, 1))
                if (len(pending) >= ring
                        or any(self.display_now(step + i)
                               for i in range(n))):
                    _drain()
                if (self.debug_step is not None
                        and any(self.display_now(step + i) for i in range(n))):
                    # debug norms reflect the post-chunk params, so label
                    # them with the chunk's last step, not a mid-chunk one
                    s_dbg = step + n - 1
                    outs, grads = self.debug_step(
                        params, last_dbg[0], s_dbg,
                        jax.random.fold_in(rng, s_dbg))
                    self.log(f"step-{s_dbg} debug:\n" +
                             self.train_net.debug_info(params, outs, grads))
                if self.elastic is not None:
                    # chunks are cut so at most the LAST step is a sync step
                    params = self.elastic.maybe_sync(
                        step + n - 1, params,
                        rng=jax.random.fold_in(rng, step + n - 1))
                last = step + n - 1
                if (ckpt is not None and self.cfg.checkpoint_frequency > 0
                        and last >= self.cfg.checkpoint_after_steps
                        and (last + 1) % self.cfg.checkpoint_frequency == 0):
                    # drain BEFORE the save: every hook/metric below the
                    # snapshot step has fired (and the health monitor
                    # has classified every step the snapshot contains),
                    # so a crash-and-restore never leaves a hook gap —
                    # and a poisoned state never reaches the save
                    _drain()
                    self._save_checkpoint(ckpt, last + 1, params,
                                          opt_state)
                step += n
            _drain()
        finally:
            # an exception mid-loop (injected fault, data failure) must
            # not leave our signal handlers installed in the
            # supervisor's process, nor the feed thread running
            if fd is not None:
                fd.close()
            self._ckpt_unguard(old_handlers)
        if (ckpt is not None and not interrupted
                and self.cfg.train_steps > start_step):
            self._save_checkpoint(ckpt, self.cfg.train_steps, params,
                                  opt_state)
        return params, opt_state, history

    def _grad_poison(self, n: int):
        """Consult the `step.grad` fault site once per step about to be
        dispatched; an (n,) float32 scale vector when any fires, else
        None (the common case — the compiled program is untouched and
        no extra operand is transferred)."""
        if faults.active() is None:
            return None
        from ..utils.health import SPIKE_SCALE
        codes = [faults.maybe_fault("step.grad") for _ in range(n)]
        if not any(codes):
            return None
        import numpy as np
        scale = {"nan": float("nan"), "spike": SPIKE_SCALE}
        return np.asarray([scale.get(c, 1.0) for c in codes], np.float32)

    def _call_hook(self, hook, step, metrics) -> None:
        """User hooks are observers, not training logic: one raising
        must not look like a step failure (it would burn a Supervisor
        restart) — log and continue."""
        try:
            hook(step, metrics)
        except Exception as e:  # noqa: BLE001 — any user-hook failure
            name = getattr(hook, "__name__", repr(hook))
            self.log(f"warning: user hook {name} raised at step {step} "
                     f"({type(e).__name__}: {e}); continuing")

    def _save_checkpoint(self, ckpt, step, params, opt_state) -> bool:
        """Cadence/final/signal snapshot, gated on the health verdict:
        a window the monitor classified as fatal is REFUSED (restoring
        it would faithfully resume the divergence), a suspect (spike)
        window saves but carries its verdict in MANIFEST.json so
        `skip_unhealthy` restores can walk past it."""
        if ckpt is None:
            return False
        if self.health is None:
            ckpt.save(step, *self._ckpt_state(params, opt_state))
            self._publish(step, None)
            return True
        if not self.health.ok_to_save():
            rec = self.health.snapshot_health()
            self.log(f"health: refusing checkpoint at step {step} "
                     f"(verdict {rec['verdict']!r} — restoring this "
                     f"snapshot would resume the divergence)")
            obs.emit_event("ckpt.refused", step=step,
                           verdict=rec["verdict"])
            return False
        rec = self.health.snapshot_health()
        ckpt.save(step, *self._ckpt_state(params, opt_state),
                  health=rec)
        self.health.mark_snapshot()
        self._publish(step, rec.get("verdict"))
        return True

    def _publish(self, step: int, verdict) -> None:
        """Fire the post-save publication hook (`on_checkpoint`).
        Runs after the snapshot (and its manifest verdict) is on disk
        — the point where a serving tier may trust the step.  Hook
        failures are logged observer-style, exactly like user hooks:
        publication is telemetry for the loop, not training logic."""
        hook = self.on_checkpoint
        if hook is None:
            return
        try:
            hook(step, verdict)
        except Exception as e:  # noqa: BLE001 — observer, not logic
            self.log(f"warning: checkpoint publish hook raised at "
                     f"step {step} ({type(e).__name__}: {e}); "
                     f"continuing")

    def apply_lr_backoff(self, factor: float) -> float:
        """Scale the effective learning rate by `factor` (the
        Supervisor's divergence-rescue knob) and rebuild the compiled
        steps — the schedule value is baked in at trace time, so the
        jitted programs must be re-traced for the scale to apply.
        Returns the cumulative scale."""
        self.updater.lr_scale *= float(factor)
        self._build_steps(self._donate)
        self.log(f"health: learning-rate backoff x{factor:g} applied "
                 f"(cumulative scale {self.updater.lr_scale:g})")
        return self.updater.lr_scale

    def _ckpt_state(self, params, opt_state):
        """Checkpoint payload: padded-storage params/opt state (uneven
        partition dims, parallel/partition.py pad_params) sliced back
        to spec shapes so checkpoints stay mesh-portable — a restore
        under any mesh (or none) re-pads via shard_params."""
        net = self.train_net
        return (net.unpad_params(params),
                {k: net.unpad_params(t) for k, t in opt_state.items()})

    def _ckpt_guard(self, workspace):
        """(ckpt_manager, interrupted, old_handlers) — the shared
        checkpoint + SIGTERM/SIGINT machinery of run()/run_cd().  Pair
        with _ckpt_unguard(old_handlers)."""
        ckpt = None
        if workspace and self.cfg.checkpoint_frequency > 0:
            from ..utils.checkpoint import CheckpointManager
            ckpt = CheckpointManager(workspace, log_fn=self.log)
        interrupted: List[int] = []
        old_handlers: Dict[Any, Any] = {}
        if ckpt is not None:
            import signal

            def _on_signal(signum, frame):
                interrupted.append(signum)

            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    old_handlers[sig] = signal.signal(sig, _on_signal)
                except ValueError:   # non-main thread: no signal hooks
                    break
        return ckpt, interrupted, old_handlers

    @staticmethod
    def _ckpt_unguard(old_handlers) -> None:
        if old_handlers:
            import signal
            for sig, h in old_handlers.items():
                signal.signal(sig, h)

    def run_cd(self, params, opt_state, train_iter: Iterator,
               test_iter_factory=None, val_iter_factory=None,
               hooks: Optional[List[Callable[[int, Dict], None]]] = None,
               scan_chunk: int = 0,
               start_step: int = 0, seed: int = 0,
               workspace: Optional[str] = None):
        """kContrastiveDivergence training (ModelProto.alg,
        model.proto:40-44): greedy layer-wise CD-k over the net's kRBM
        layers.  The training budget splits evenly across RBMs (classic
        greedy stacking: each trains on the hidden probabilities of the
        ones before it); the parser prefix and each RBM's Gibbs chain
        run in one jitted step, updates through the ordinary Updater.
        RBMProto.persistent runs PCD: the Gibbs chain continues from
        the previous step's chain end instead of the data batch.
        Checkpoint cadence and SIGTERM/SIGINT snapshots behave exactly
        as in run() (PCD chain state is per-run and restarts from the
        data on resume — standard PCD practice)."""
        import functools

        from ..models.rbm import cd_grads

        net = self.train_net
        rbm_names = [n for n in net.topo
                     if getattr(net.layers[n], "is_rbm", False)]
        if not rbm_names:
            raise ValueError("alg kContrastiveDivergence needs at least "
                             "one kRBM layer in the net")
        mesh, cdtype = self.mesh, self.compute_dtype
        updater, mults = self.updater, self.multipliers

        @functools.partial(jax.jit, static_argnums=(4,))
        def cd_step(params, opt_state, batch, rng, idx, step, chain):
            name = rbm_names[idx]
            layer = net.layers[name]
            prefix = net.topo[:net.topo.index(name)]
            _, _, outputs = net.apply(params, batch, train=False,
                                      mesh=mesh, compute_dtype=cdtype,
                                      layer_subset=prefix)
            v = outputs[layer.cfg.srclayers[0]]
            v = v.reshape(v.shape[0], -1).astype(jnp.float32)
            grads, recon, chain_end = cd_grads(
                layer.cd_view(params), v, rng, k=layer.cd_k,
                persistent=chain)
            named = layer.named_grads(grads)
            sub_p = {k: params[k] for k in named}
            sub_s = {sk: {k: sv[k] for k in named}
                     for sk, sv in opt_state.items()}
            sub_m = {k: mults[k] for k in named}
            new_p, new_s = updater.update(step, named, sub_p, sub_s,
                                          multipliers=sub_m)
            params = {**params, **new_p}
            opt_state = {sk: {**opt_state[sk], **new_s[sk]}
                         for sk in opt_state}
            return params, opt_state, recon, chain_end

        if scan_chunk and scan_chunk > 1:
            self.log("warning: scan_chunk is not supported for CD "
                     "training (host-side greedy phase switching); "
                     "running per-step")
        for nm, it, step_fn in (("test", test_iter_factory, self.test_step),
                                ("validation", val_iter_factory,
                                 self.val_step)):
            if it is not None and step_fn is None:
                self.log(f"warning: {nm} iterator supplied but this CD "
                         f"net built no {nm} eval step (no loss layer "
                         f"in that phase); skipping {nm} evaluation "
                         "(reconstruction error is the training metric)")

        total = self.cfg.train_steps
        n = len(rbm_names)
        rng = jax.random.PRNGKey(seed ^ 0xCD)
        history: List[Dict[str, float]] = []
        chains: Dict[int, Any] = {}   # PCD chain per RBM index
        ckpt, interrupted, old_handlers = self._ckpt_guard(workspace)
        step = start_step
        try:
            for step in range(start_step, total):
                faults.maybe_fault("step.train")
                if interrupted:
                    self.log(f"signal {interrupted[0]} received: "
                             f"checkpointing at step {step} and stopping")
                    ckpt.save(step, *self._ckpt_state(params, opt_state))
                    break
                if (self.test_step and self.test_now(step)
                        and test_iter_factory):
                    avg = self.evaluate(params, test_iter_factory(),
                                        self.cfg.test_steps, self.test_step)
                    self.log(f"step-{step} test: " + ", ".join(
                        f"{k} : {v:.6f}" for k, v in sorted(avg.items())))
                if (self.val_step and self.validate_now(step)
                        and val_iter_factory):
                    avg = self.evaluate(params, val_iter_factory(),
                                        self.cfg.validation_steps,
                                        self.val_step)
                    self.log(f"step-{step} validation: " + ", ".join(
                        f"{k} : {v:.6f}" for k, v in sorted(avg.items())))
                idx = min(step * n // max(total, 1), n - 1)
                layer = net.layers[rbm_names[idx]]
                batch = next(train_iter)
                params, opt_state, recon, chain_end = cd_step(
                    params, opt_state, batch, jax.random.fold_in(rng, step),
                    idx, step, chains.get(idx) if layer.persistent else None)
                if layer.persistent:
                    chains[idx] = chain_end
                self.perf.update({"recon": recon})
                if hooks:
                    m_cd = {"recon": float(recon), "rbm": idx}
                    for h in hooks:
                        self._call_hook(h, step, m_cd)
                if self.display_now(step):
                    self.log(f"step-{step} cd[{rbm_names[idx]}]: "
                             f"{self.perf.to_string()}")
                    history.append({"step": step, "rbm": idx,
                                    **self.perf.averages()})
                    self.perf.reset()
                if (ckpt is not None and self.cfg.checkpoint_frequency > 0
                        and step >= self.cfg.checkpoint_after_steps
                        and (step + 1) % self.cfg.checkpoint_frequency == 0):
                    ckpt.save(step + 1, *self._ckpt_state(params, opt_state))
        finally:
            self._ckpt_unguard(old_handlers)
        if ckpt is not None and not interrupted and total > start_step:
            ckpt.save(total, *self._ckpt_state(params, opt_state))
        return params, opt_state, history

    def resume(self, params, opt_state, workspace: str,
               skip_unhealthy: bool = False):
        """Restore the latest snapshot (Worker::Resume, finally real).
        Returns (params, opt_state, start_step).  `skip_unhealthy`
        walks back past snapshots whose recorded health verdict is not
        "ok" (the Supervisor's divergence rescue — restore the last
        numerically GOOD state, not the last readable one).

        Checkpoints are saved spec-shaped (_ckpt_state unpads the
        pad-to-divisible storage of uneven partition dims), so the
        restore template must be spec-shaped too — the caller may hand
        us padded, sharded live arrays (main.py resumes AFTER
        shard_params).  After the restore, re-pad + re-shard under the
        trainer's mesh so the padded sharded layout survives a
        resume."""
        from ..utils.checkpoint import CheckpointManager
        net = self.train_net
        # abstract template: checkpoint-shaped (spec, unpadded) leaves
        # WITHOUT materializing sliced copies of the live state — at
        # restore time the live padded arrays, a concrete template, and
        # the restored arrays would otherwise coexist.  Each leaf
        # carries an explicit sharding (the live array's where the
        # shapes match; replicated for pad-sliced leaves, re-sharded
        # below) so the restore never depends on the sharding recorded
        # in the checkpoint — which may come from a different topology.
        tpl_p, tpl_o = jax.eval_shape(self._ckpt_state, params, opt_state)

        def shard_tpl(tpl, live):
            rep = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(self.mesh, PartitionSpec())
            out = {}
            for k, t in tpl.items():
                arr = live.get(k)
                sh = (arr.sharding
                      if (hasattr(arr, "sharding")
                          and tuple(arr.shape) == tuple(t.shape))
                      else rep)
                out[k] = (jax.ShapeDtypeStruct(t.shape, t.dtype,
                                               sharding=sh)
                          if sh is not None else t)
            return out

        tpl_p = shard_tpl(tpl_p, params)
        tpl_o = {k: shard_tpl(t, opt_state.get(k, {}))
                 for k, t in tpl_o.items()}
        restored = CheckpointManager(workspace, log_fn=self.log).restore(
            template={"params": tpl_p, "opt_state": tpl_o},
            skip_unhealthy=skip_unhealthy)
        if restored is None:
            return params, opt_state, 0
        rp, ro, step = restored
        if self.mesh is not None:
            from ..parallel import shard_opt_state, shard_params
            rp = shard_params(self.mesh, net, rp)
            ro = shard_opt_state(self.mesh, net, ro)
        return rp, ro, step
