"""NeuralNet: NetProto config → a single pure, jittable step function.

Reference: /root/reference/src/worker/neuralnet.cc.  Same construction
semantics — graph from `srclayers` edges, topological sort
(neuralnet.cc:72-110, graph.cc:80-101), per-phase layer filtering by
`exclude` (worker.cc:72-86), Setup() shape inference in topo order —
but instead of an interpreter walking layers per step, the whole forward
(+ loss) is a pure function of (params, batch) that `jax.grad` and
`jax.jit` turn into one compiled XLA program.  Weight sharing between
train/test nets (neuralnet.cc:379-391 ShareWeights) is implicit: both
phases apply different nets to the *same* params pytree.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config.schema import LayerConfig, ModelConfig, NetConfig
from .graph import Graph
from .init import init_param
from .layers import Context, Layer, LayerError, ParamSpec, create_layer
from .updater import Multipliers


def _pad_excess(spec: ParamSpec, arr) -> bool:
    """Whether `arr` is `spec` plus a pad-to-divisible tail on the
    partition dim (parallel/partition.py pad_params) — the ONE shape
    mismatch that slice-at-use (_resolve_params) and unpad-at-save
    (unpad_params) agree to absorb.  Anything else is a config mismatch
    that must keep failing loudly downstream."""
    d = spec.partition_dim
    return (d is not None and 0 <= d < len(spec.shape)
            and len(arr.shape) == len(spec.shape)
            and arr.shape[d] > spec.shape[d]
            and all(a == s for i, (a, s) in
                    enumerate(zip(arr.shape, spec.shape))
                    if i != d))


class NeuralNet:
    def __init__(self, net_cfg: NetConfig, phase: str = "kTrain",
                 input_shapes: Optional[Dict[str, Dict[str, tuple]]] = None,
                 batchsize: Optional[int] = None):
        """input_shapes: data-layer name → field → per-sample shape
        (no batch dim), e.g. {"data": {"pixel": (28, 28), "label": ()}}.
        `batchsize` overrides DataProto.batchsize for all data layers.

        `remat_types` (attribute): layer type strings to wrap in
        jax.checkpoint — an opt-in knob for memory-tight stacks.  Empty
        by default: LRN, the one type it used to list, now carries a
        hand-written custom_vjp (ops/lrn.py) whose residuals are cheaper
        than the remat recompute was (autodiff through checkpoint built
        bitpacked-mask fusion soup costing ~10% of the AlexNet step).
        """
        self.phase = phase
        self.cfgs: List[LayerConfig] = [
            l for l in net_cfg.layer if phase not in l.exclude]
        self.input_shapes = input_shapes or {}
        self.batchsize_override = batchsize
        # NetProto.partition_type is the per-layer default;
        # LayerProto.partition_type overrides it (neuralnet.cc:45-56,
        # 198-323) — consumed as GSPMD sharding constraints in apply()
        self.default_partition = net_cfg.partition_type
        self._partition_warned: set = set()

        self.graph = Graph()
        for l in self.cfgs:
            self.graph.add_node(l.name, type=l.type)
        names = {l.name for l in self.cfgs}
        for l in self.cfgs:
            for src in l.srclayers:
                if src not in names:
                    raise LayerError(
                        f"layer {l.name!r}: unknown srclayer {src!r} "
                        f"in phase {phase}")
                self.graph.add_edge(src, l.name)
        self.topo = self.graph.topo_sort()

        self.layers: Dict[str, Layer] = {
            l.name: create_layer(l) for l in self.cfgs}
        self._setup()
        self._build_param_index()
        self._fuse_relu_lrn()
        self.remat_types: set = set()

    # -- construction ------------------------------------------------------
    def _setup(self) -> None:
        shapes: Dict[str, Any] = {}
        for name in self.topo:
            layer = self.layers[name]
            src_shapes = [self._src_shape(shapes, src, name)
                          for src in layer.cfg.srclayers]
            if layer.is_data:
                sample = self.input_shapes.get(name)
                if sample is None:
                    raise LayerError(
                        f"data layer {name!r} needs input_shapes entry")
                layer.setup(src_shapes, sample_shapes=sample)
                if self.batchsize_override:
                    layer.batchsize = self.batchsize_override
                    layer.out_shape = {
                        k: (self.batchsize_override,) + tuple(v)
                        for k, v in sample.items()}
            else:
                layer.setup(src_shapes)
            shapes[name] = layer.out_shape
        self.shapes = shapes

    def _src_shape(self, shapes: Dict[str, Any], src: str, dst: str):
        out = shapes[src]
        if isinstance(out, tuple) and out and isinstance(out[0], tuple):
            # Slice layer: consumer i gets view i (base_layer.cc:114-173)
            return out[self._consumer_index(src, dst)]
        return out

    def _consumer_index(self, src: str, dst: str) -> int:
        return self.graph.dsts_of(src).index(dst)

    def _fuse_relu_lrn(self) -> None:
        """Mark conv→relu→lrn chains for the fused relu+lrn custom_vjp
        (ops/lrn.py): the LRN layer reads the pre-relu tensor and
        applies ReLU inside the vjp (see LRNLayer.fuse_from).  The ReLU
        layer still produces its output for any other consumer; XLA
        removes it when unused."""
        from .layers import LRNLayer, ReLULayer, SliceLayer
        for name in self.topo:
            layer = self.layers[name]
            if not isinstance(layer, LRNLayer):
                continue
            if len(layer.cfg.srclayers) != 1:
                continue
            src = self.layers[layer.cfg.srclayers[0]]
            if (isinstance(src, ReLULayer) and src.slope == 0.0
                    and len(src.cfg.srclayers) == 1
                    and not isinstance(self.layers[src.cfg.srclayers[0]],
                                       SliceLayer)):
                layer.fuse_from = src.cfg.srclayers[0]

    def _build_param_index(self) -> None:
        self.param_specs: Dict[str, ParamSpec] = {}
        self.param_aliases: Dict[str, str] = {}
        for name in self.topo:
            layer = self.layers[name]
            shared = list(layer.cfg.share_param)
            for i, spec in enumerate(layer.param_specs):
                if i < len(shared):
                    # share_param: this layer's i-th param aliases another
                    # layer's param (model.proto:137); key is the canonical
                    # "<layer>/<name>" of the owner.
                    self.param_aliases[spec.name] = shared[i]
                else:
                    self.param_specs[spec.name] = spec

    # -- params ------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Dict[str, jnp.ndarray]:
        params = {}
        keys = jax.random.split(rng, max(len(self.param_specs), 1))
        for k, (name, spec) in zip(keys, sorted(self.param_specs.items())):
            params[name] = init_param(k, spec.cfg, spec.shape, spec.fan_in)
        return params

    def multipliers(self) -> Dict[str, Multipliers]:
        return {name: Multipliers(spec.cfg.learning_rate_multiplier,
                                  spec.cfg.weight_decay_multiplier)
                for name, spec in self.param_specs.items()}

    def partition_dims(self) -> Dict[str, int]:
        """ParamProto.partition_dim per param — consumed by
        singa_tpu.parallel.partition to build NamedShardings."""
        return {name: spec.partition_dim
                for name, spec in self.param_specs.items()}

    def layer_partition(self, name: str) -> str:
        """Effective partition_type of a layer: LayerProto override,
        else the NetProto default (neuralnet.cc:45-56)."""
        lp = self.layers[name].cfg.partition_type
        return lp if lp is not None else self.default_partition

    def _constrain(self, out, name: str, mesh):
        """GSPMD successor of the reference's connector insertion
        (neuralnet.cc:198-323): a partition_type on a layer becomes a
        sharding constraint on its activation —
          kDataPartition  → batch dim over "data"
          kLayerPartition → feature (last) dim over "model"
          kNone           → fully replicated
        and XLA compiles the Slice/Concate/Split/Bridge data movement
        the reference hand-coded for every src→dst combination.  A dim
        that doesn't divide the mesh axis still partitions: GSPMD tiles
        with an implicit pad on the last shard — the compiler-native
        form of the reference giving the remainder to the last
        partition (neuralnet.cc:160-162)."""
        import jax.numpy as _jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if mesh is None or not isinstance(out, _jnp.ndarray) or out.ndim == 0:
            return out
        ptype = self.layer_partition(name)
        if ptype is None or ptype == "kNone":
            return out
        if ptype == "kDataPartition":
            axis, dim = "data", 0
        elif ptype == "kLayerPartition":
            axis, dim = "model", out.ndim - 1
        else:
            return out
        n = dict(mesh.shape).get(axis, 1)
        if n <= 1:
            return out
        spec = [None] * out.ndim
        spec[dim] = axis
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(*spec)))

    def _resolve_params(self, params: Dict[str, jnp.ndarray]):
        full = dict(params)
        # padded storage (parallel/partition.py pad_params): an array
        # larger than its spec in the PARTITION dim (and exact in every
        # other dim — anything else is a config mismatch that must keep
        # failing loudly) carries a pad-to-divisible tail so uneven
        # dims shard instead of replicating; slice it off at use.  Zero
        # pad + slice keeps the training closure exact (pad grads are
        # the slice transpose: zero), so the layout is invisible to
        # layers and decode; checkpoints are saved UNPADDED
        # (unpad_params at the save boundary) so they stay
        # mesh-portable.
        for name, spec in self.param_specs.items():
            arr = full.get(name)
            if arr is None or not hasattr(arr, "shape"):
                continue
            if _pad_excess(spec, arr):
                full[name] = jax.lax.slice(
                    arr, (0,) * len(spec.shape), spec.shape)
        for alias, owner in self.param_aliases.items():
            if owner not in full:
                raise LayerError(f"share_param target {owner!r} not found")
            full[alias] = full[owner]
        return full

    def unpad_params(self, params: Dict[str, jnp.ndarray]):
        """Slice padded-storage params (see _resolve_params) back to
        their spec shapes — used at the checkpoint save boundary so
        checkpoints stay spec-shaped and mesh-portable (Trainer.resume
        re-pads via shard_params).  Only a partition-dim excess is
        sliced, mirroring _resolve_params: any other shape mismatch is
        a config error that must keep failing loudly, not be silently
        cropped into a checkpoint."""
        out = {}
        for name, arr in params.items():
            spec = self.param_specs.get(name)
            if (spec is not None and hasattr(arr, "shape")
                    and _pad_excess(spec, arr)):
                arr = arr[tuple(slice(0, s) for s in spec.shape)]
            out[name] = arr
        return out

    def _constrain_uneven_params(self, full, mesh):
        """Partition the COMPUTE on params whose partition dim doesn't
        divide their mesh axis.  Storage for such a param stays
        replicated (jax.device_put only tiles divisible dims), but an
        in-step sharding constraint makes GSPMD tile it with an
        implicit last-shard pad — so e.g. a 10-wide classifier on
        model=4 runs 3/3/3/1-partitioned, the reference's
        last-partition-remainder contract (neuralnet.cc:160-162,
        base_layer.cc:125-129) — instead of silently replicating the
        matmul a user asked to split."""
        if mesh is None:
            return full
        from jax.sharding import NamedSharding, PartitionSpec as P
        shape = dict(mesh.shape)
        for name, spec in self.param_specs.items():
            dim, axis = spec.partition_dim, (spec.mesh_axis or "model")
            n = shape.get(axis, 1)
            if (n > 1 and dim is not None and dim >= 0
                    and spec.shape[dim] % n and name in full):
                sp: list = [None] * len(spec.shape)
                sp[dim] = axis
                full[name] = jax.lax.with_sharding_constraint(
                    full[name], NamedSharding(mesh, P(*sp)))
        return full

    # -- forward -----------------------------------------------------------
    def apply(self, params: Dict[str, jnp.ndarray], batch: Dict[str, Any],
              rng: Optional[jax.Array] = None, train: Optional[bool] = None,
              mesh=None, compute_dtype=None,
              layer_subset: Optional[List[str]] = None,
              outputs: Optional[Dict[str, Any]] = None, step=None
              ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], Dict[str, Any]]:
        """Run the net. Returns (total_loss, metrics, outputs).

        metrics aggregates every loss layer's dict (the reference's
        Performance blob, worker.cc:350-386); outputs maps layer name →
        activation (the reference's per-layer data_ blobs).

        `layer_subset` (topo-ordered subsequence of self.topo) runs only
        those layers, reading/extending the caller's `outputs` dict —
        the pipeline runtime (parallel.pipeline_net) uses this to run
        the pre/post groups through the SAME per-layer semantics
        (fuse_from, remat, aux losses) as a plain forward.
        """
        if train is None:
            train = self.phase == "kTrain"
        full = self._constrain_uneven_params(
            self._resolve_params(params), mesh)
        ctx_batch = batch
        outputs = {} if outputs is None else outputs
        metrics: Dict[str, jnp.ndarray] = {}
        total_loss = jnp.zeros((), jnp.float32)
        names = self.topo if layer_subset is None else layer_subset
        topo_index = {n: i for i, n in enumerate(self.topo)}
        for name in names:
            idx = topo_index[name]
            layer = self.layers[name]
            fuse_from = getattr(layer, "fuse_from", "")
            if fuse_from:
                srcs = [outputs[fuse_from]]
            else:
                srcs = [self._src_out(outputs, src, name)
                        for src in layer.cfg.srclayers]
            ctx = Context(batch=ctx_batch, train=train, rng=rng,
                          layer_index=idx, mesh=mesh,
                          compute_dtype=compute_dtype, step=step)
            if layer.cfg.type in self.remat_types:
                out = jax.checkpoint(
                    lambda *s, _l=layer, _c=ctx: _l.apply(full, list(s), _c)
                )(*srcs)
            else:
                out = layer.apply(full, srcs, ctx)
            out = self._constrain(out, name, mesh)
            outputs[name] = out
            aux = getattr(layer, "_aux", None)
            if aux is not None:
                # auxiliary losses (e.g. MoE router balance) join the
                # objective and the metric report
                total_loss = total_loss + aux
                metrics[f"{name}/aux"] = aux
            if layer.is_loss:
                total_loss = total_loss + out["loss"]
                for k, v in out.items():
                    key = k if len(self._loss_layers()) == 1 else f"{name}/{k}"
                    metrics[key] = v
        return total_loss, metrics, outputs

    def _src_out(self, outputs, src, dst):
        from .layers import SliceLayer
        out = outputs[src]
        if isinstance(self.layers[src], SliceLayer):
            return out[self._consumer_index(src, dst)]
        return out

    def _loss_layers(self) -> List[str]:
        return [n for n in self.topo if self.layers[n].is_loss]

    # -- introspection -----------------------------------------------------
    def to_json(self) -> str:
        """Net-structure dump for visualization (graph.cc:4-59 parity)."""
        return self.graph.to_json()

    def debug_info(self, params: Dict[str, jnp.ndarray],
                   outputs: Dict[str, Any],
                   grads: Optional[Dict[str, jnp.ndarray]] = None) -> str:
        """Per-layer mean-absolute data norms — the reference's DebugInfo
        printout (neuralnet.cc:350-378) used when ModelProto.debug.
        The reference prints data AND gradient L1 norms; pass `grads`
        (the param-gradient pytree from the step) to include them."""
        lines = []
        for name in self.topo:
            out = outputs.get(name)
            if isinstance(out, jnp.ndarray) and out.dtype != jnp.int32:
                lines.append(f"{name}: data {jnp.mean(jnp.abs(out)):.6f}")
        for pname, p in sorted(params.items()):
            line = f"{pname}: param {jnp.mean(jnp.abs(p)):.6f}"
            if grads is not None and pname in grads:
                line += f" grad {jnp.mean(jnp.abs(grads[pname])):.6f}"
            lines.append(line)
        return "\n".join(lines)


def build_net(model_cfg: ModelConfig, phase: str = "kTrain",
              input_shapes=None, batchsize=None) -> NeuralNet:
    if model_cfg.neuralnet is None:
        raise LayerError("model config has no neuralnet section")
    return NeuralNet(model_cfg.neuralnet, phase, input_shapes, batchsize)
