"""Parameter initialization — the reference's init methods plus modern ones.

Reference: Param::Init, /root/reference/src/utils/param.cc:61-99 and the
InitMethod enum model.proto:72-93.  Semantics preserved exactly:

  kConstant            value
  kUniform             U(low, high) * value
  kUniformSqrtFanIn    U(low, high) * value / sqrt(fan_in / 3)
  kUniformSqrtFanInOut U(low, high) * value / sqrt(shape[0] + shape[1])
  kGaussain            N(mean, std) * value
  kGaussainSqrtFanIn   N(mean, std) * value / sqrt(shape[0])
  kPretrained          loaded from checkpoint (handled by the trainer)

`fan_in` follows the reference's per-layer convention: conv passes
C*k*k (layer.cc:48), inner-product passes vdim*hdim (layer.cc:174 —
note: the reference passes the full weight count, we reproduce that).
The reference multiplies by `value` only when value != 0 (protobuf
default 1), mirrored here.

TPU-native additions: kXavier (Glorot uniform), kMSRA (He normal).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ..config.schema import ParamConfig


def init_param(rng: jax.Array, cfg: ParamConfig, shape: Sequence[int],
               fan_in: int = 0, dtype=jnp.float32) -> jnp.ndarray:
    shape = tuple(shape)
    method = cfg.init_method
    value = cfg.value
    if method == "kConstant":
        return jnp.full(shape, value, dtype)
    if method == "kUniform":
        x = jax.random.uniform(rng, shape, dtype, cfg.low, cfg.high)
        return x * value if value else x
    if method == "kUniformSqrtFanIn":
        x = jax.random.uniform(rng, shape, dtype, cfg.low, cfg.high)
        if value:
            x = x * (value / math.sqrt(fan_in / 3.0))
        return x
    if method == "kUniformSqrtFanInOut":
        x = jax.random.uniform(rng, shape, dtype, cfg.low, cfg.high)
        if value:
            x = x * (value / math.sqrt(shape[0] + shape[1]))
        return x
    if method == "kGaussain":
        x = cfg.mean + cfg.std * jax.random.normal(rng, shape, dtype)
        return x * value if value else x
    if method == "kGaussainSqrtFanIn":
        x = cfg.mean + cfg.std * jax.random.normal(rng, shape, dtype)
        if value:
            x = x * (value / math.sqrt(shape[0]))
        return x
    if method == "kXavier":
        limit = math.sqrt(6.0 / (shape[0] + shape[-1]))
        return jax.random.uniform(rng, shape, dtype, -limit, limit)
    if method == "kMSRA":
        std = math.sqrt(2.0 / max(fan_in, 1))
        return std * jax.random.normal(rng, shape, dtype)
    if method == "kPretrained":
        raise ValueError(
            "kPretrained params must be restored from a checkpoint "
            "(see singa_tpu.utils.checkpoint), not re-initialized")
    raise ValueError(f"unknown init_method {method!r}")
