from .init import init_param
from .updater import Updater, Multipliers, learning_rate, make_updater
from .graph import Graph, GraphError
from .layers import (Layer, LayerError, ParamSpec, Context, create_layer,
                     register_layer, LAYER_REGISTRY)
from .net import NeuralNet, build_net
from .trainer import Trainer, Performance, TimerInfo
from .supervisor import Supervisor, TrainingAborted, FailureRecord
from .pipeline import PipelineController, PipelineSpec
