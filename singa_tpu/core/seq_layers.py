"""Sequence-model layer family: the transformer extension of the layer
zoo, declared in the same NetProto-style config IR as the conv layers.

New capability (SURVEY.md §5: the reference predates attention) exposed
"the same way the reference exposes partitioning, i.e. as declarative
config": attention_param.seq_parallel selects none/ring/ulysses; expert
parallelism comes from MoE expert-stacked params sharded over the
mesh's "expert" axis; tensor parallelism from partition_dim on the
projection weights.

Layer types: kSequenceData, kEmbed, kRMSNorm, kAttention, kFeedForward,
kMoE, kLMHead.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..config.schema import ParamConfig
from ..ops import moe as moe_ops
from ..ops.attention import (attention_reference, expand_kv_heads,
                             flash_attention, rope)
from .layers import Layer, LayerError, register_layer

# (layer name, seq_len, head_dim) triples that already warned about
# the dense-fallback path
_flash_fallback_warned: set = set()


def _gaussian(std: float) -> ParamConfig:
    return ParamConfig(init_method="kGaussain", mean=0.0, std=std)


def _declare_with_default(layer: Layer, i: int, name: str, shape,
                          init_std: float, partition_dim: int = -1,
                          mesh_axis: Optional[str] = None) -> str:
    """Declare a param with a Gaussian default when the config gives no
    explicit ParamProto (transformer configs usually don't)."""
    from .layers import ParamSpec
    if i < len(layer.cfg.param):
        key = layer._declare(i, name, shape, fan_in=shape[0],
                             partition_dim=partition_dim)
        layer.param_specs[-1].mesh_axis = mesh_axis
        return key
    pcfg = _gaussian(init_std)
    key = f"{layer.name}/{name}"
    layer.param_specs.append(
        ParamSpec(key, tuple(shape), shape[0], pcfg, partition_dim,
                  mesh_axis))
    return key


@register_layer("kSequenceData")
class SequenceDataLayer(Layer):
    """Token-sequence input: ctx.batch[name] = {"input": (B,S) int32,
    "target": (B,S) int32}."""

    is_data = True

    def setup(self, src_shapes, sample_shapes: Optional[Dict] = None):
        p = self.cfg.seqdata_param
        bs = p.batchsize if p else (self.cfg.data_param.batchsize
                                    if self.cfg.data_param else 0)
        seq = p.seq_len if p else 0
        self.batchsize, self.seq_len = bs, seq
        self.vocab_size = p.vocab_size if p else 0
        if sample_shapes:
            self.out_shape = {k: (bs,) + tuple(v)
                              for k, v in sample_shapes.items()}
        else:
            self.out_shape = {"input": (bs, seq), "target": (bs, seq)}

    def apply(self, params, srcs, ctx):
        return ctx.batch[self.name]


@register_layer("kEmbed")
class EmbedLayer(Layer):
    """Token embedding: (B, S) int32 → (B, S, E)."""

    def setup(self, src_shapes):
        p = self.cfg.embed_param
        if p is None or not p.vocab_size or not p.embed_dim:
            raise LayerError(f"{self.name}: embed_param vocab_size/embed_dim "
                             "required")
        src = src_shapes[0]
        shape = src["input"] if isinstance(src, dict) else tuple(src)
        self.out_shape = tuple(shape) + (p.embed_dim,)
        self.w_key = _declare_with_default(
            self, 0, "embedding", (p.vocab_size, p.embed_dim),
            init_std=1.0 / math.sqrt(p.embed_dim), partition_dim=1)

    def apply(self, params, srcs, ctx):
        src = srcs[0]
        tokens = src["input"] if isinstance(src, dict) else src
        emb = params[self.w_key]
        if ctx.compute_dtype is not None:
            emb = emb.astype(ctx.compute_dtype)
        return jnp.take(emb, tokens.astype(jnp.int32), axis=0)


@register_layer("kSeqLabel")
class SeqLabelLayer(Layer):
    """Next-token targets from the sequence data dict."""

    def setup(self, src_shapes):
        self.out_shape = tuple(src_shapes[0]["target"])

    def apply(self, params, srcs, ctx):
        return srcs[0]["target"]


@register_layer("kRMSNorm")
class RMSNormLayer(Layer):
    def setup(self, src_shapes):
        p = self.cfg.rmsnorm_param
        self.eps = p.epsilon if p else 1e-6
        s = tuple(src_shapes[0])
        self.out_shape = s
        key = f"{self.name}/scale"
        from .layers import ParamSpec
        self.param_specs.append(ParamSpec(
            key, (s[-1],), 0, ParamConfig(init_method="kConstant", value=1.0)))
        self.w_key = key

    def apply(self, params, srcs, ctx):
        x = srcs[0]
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        y = x * jax.lax.rsqrt(var + self.eps).astype(x.dtype)
        return y * params[self.w_key].astype(x.dtype)


@register_layer("kAttention")
class AttentionLayer(Layer):
    """Multi-head (GQA) causal self-attention with RoPE.

    seq_parallel: "none" → Pallas flash attention on the local chunk;
    "ring" / "ulysses" → sequence-parallel attention over the mesh's
    "seq" axis (singa_tpu.parallel.sequence).
    """

    def setup(self, src_shapes):
        p = self.cfg.attention_param
        if p is None:
            raise LayerError(f"{self.name}: attention_param required")
        b, s, e = tuple(src_shapes[0])
        self.heads = p.num_heads
        self.kv_heads = p.num_kv_heads or p.num_heads
        self.head_dim = p.head_dim
        self.causal = p.causal
        self.seq_parallel = p.seq_parallel
        self.use_rope = p.rope
        self.rope_theta = p.rope_theta
        self.out_shape = (b, s, e)
        hd = self.heads * self.head_dim
        kvd = self.kv_heads * self.head_dim
        std = 1.0 / math.sqrt(e)
        self.wq = _declare_with_default(self, 0, "wq", (e, hd), std, 1)
        self.wk = _declare_with_default(self, 1, "wk", (e, kvd), std, 1)
        self.wv = _declare_with_default(self, 2, "wv", (e, kvd), std, 1)
        self.wo = _declare_with_default(self, 3, "wo", (hd, e), std, 0)

    def _proj(self, params, key, x, ctx):
        w = params[key]
        if ctx.compute_dtype is not None:
            w = w.astype(ctx.compute_dtype)
        return jnp.einsum("bse,ed->bsd", x, w,
                          preferred_element_type=jnp.float32).astype(x.dtype)

    def qkv(self, params, x, positions, ctx):
        """Projection + head-split + RoPE prologue, shared by training
        `apply` and the KV-cache decode path (models/generate.py).
        `positions`: (S,) absolute token positions for RoPE.  Returns
        q (B, H, S, D) and k, v (B, Hkv, S, D) — pre-GQA-expansion."""
        b, s, e = x.shape
        q = self._proj(params, self.wq, x, ctx).reshape(
            b, s, self.heads, self.head_dim).transpose(0, 2, 1, 3)
        k = self._proj(params, self.wk, x, ctx).reshape(
            b, s, self.kv_heads, self.head_dim).transpose(0, 2, 1, 3)
        v = self._proj(params, self.wv, x, ctx).reshape(
            b, s, self.kv_heads, self.head_dim).transpose(0, 2, 1, 3)
        if self.use_rope:
            q = rope(q, positions, self.rope_theta)
            k = rope(k, positions, self.rope_theta)
        return q, k, v

    def _packed_eligible(self, b: int, s: int, ctx) -> bool:
        """The zero-transpose packed flash path: flash-legal shapes, GQA
        included (the kernels read each q head's group kv slice
        in-kernel — no expand_kv_heads copies).  Since round 5 mesh runs
        take it too, as a shard_map local step (batch on "data", heads
        on "model" — parallel.sequence.packed_attention_sharded), when
        the batch/head counts split evenly over those axes; "seq" must
        be unsharded (a sharded S would need offset-aware masks) and
        "pipe" never reaches here (stage bodies see ctx.mesh None)."""
        if not (self.seq_parallel == "none"
                and self.heads % self.kv_heads == 0
                and s % 128 == 0 and self.head_dim % 8 == 0):
            return False
        if ctx.mesh is None:
            return True
        shape = dict(ctx.mesh.shape)
        tp = shape.get("model", 1)
        return (shape.get("seq", 1) == 1 and shape.get("pipe", 1) == 1
                and b % max(shape.get("data", 1), 1) == 0
                and self.heads % max(tp, 1) == 0
                and self.kv_heads % max(tp, 1) == 0)

    def apply(self, params, srcs, ctx):
        x = srcs[0]
        b, s, e = x.shape
        if self._packed_eligible(b, s, ctx):
            # packed path: (B, S, H·D) end to end — the projection
            # output feeds the kernel directly and the kernel output
            # feeds wo directly.  The (B,S,H,D)→(B,H,S,D) transposes of
            # the strided path cost ~5ms/step on the 12-head S=1024
            # bench stack.
            from ..ops.attention import flash_attention_packed, rope_packed
            positions = jnp.arange(s)
            q = self._proj(params, self.wq, x, ctx)
            k = self._proj(params, self.wk, x, ctx)
            v = self._proj(params, self.wv, x, ctx)
            if self.use_rope:
                q = rope_packed(q, positions, self.heads, self.rope_theta)
                k = rope_packed(k, positions, self.kv_heads,
                                self.rope_theta)
            from ..ops.attention import flash_blocks
            bq, bk = flash_blocks(s)
            if ctx.mesh is not None:
                from ..parallel.sequence import packed_attention_sharded
                out = packed_attention_sharded(
                    q, k, v, ctx.mesh, self.heads, self.kv_heads,
                    self.causal, bq, bk)
            else:
                # custom_vjp + nondiff_argnums: positional args only
                out = flash_attention_packed(q, k, v, self.heads,
                                             self.causal, bq, bk, None,
                                             self.kv_heads)
            return self._proj(params, self.wo, out.astype(x.dtype), ctx)
        q, k, v = self.qkv(params, x, jnp.arange(s), ctx)

        if self.seq_parallel == "ring" and ctx.mesh is not None:
            # k/v stay at Hkv width: the ring rotates (and Ulysses
            # all-to-alls) unexpanded KV; group expansion happens on
            # the local chunk inside the SP step (round 5)
            from ..parallel.sequence import ring_attention
            out = ring_attention(q, k, v, ctx.mesh, "seq", self.causal)
        elif self.seq_parallel == "ulysses" and ctx.mesh is not None:
            from ..parallel.sequence import ulysses_attention
            out = ulysses_attention(q, k, v, ctx.mesh, "seq", self.causal)
        elif s % 128 == 0 and self.head_dim % 8 == 0:
            k = expand_kv_heads(k, self.heads)
            v = expand_kv_heads(v, self.heads)
            from ..ops.attention import flash_blocks
            out = flash_attention(q, k, v, self.causal, *flash_blocks(s))
        else:
            # once-keyed on (name, shape): a second model reusing a
            # layer name at a different geometry still warns
            if (self.cfg.name, s, self.head_dim) \
                    not in _flash_fallback_warned:
                _flash_fallback_warned.add(
                    (self.cfg.name, s, self.head_dim))
                import sys
                print(f"warning: attention layer {self.cfg.name!r} "
                      f"(seq_len={s}, head_dim={self.head_dim}) falls "
                      f"back to dense O(S^2)-memory attention — the "
                      f"flash kernel needs seq_len % 128 == 0 and "
                      f"head_dim % 8 == 0", file=sys.stderr)
            out = attention_reference(q, expand_kv_heads(k, self.heads),
                                      expand_kv_heads(v, self.heads),
                                      self.causal)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, -1)
        return self._proj(params, self.wo, out.astype(x.dtype), ctx)


@register_layer("kFeedForward")
class FeedForwardLayer(Layer):
    """Gated (SwiGLU) or plain MLP over (B, S, E)."""

    def setup(self, src_shapes):
        p = self.cfg.ffn_param
        if p is None or not p.hidden_dim:
            raise LayerError(f"{self.name}: ffn_param.hidden_dim required")
        b, s, e = tuple(src_shapes[0])
        f = p.hidden_dim
        if p.activation not in ("silu", "gelu", "relu"):
            raise LayerError(f"{self.name}: unknown ffn activation "
                             f"{p.activation!r} (silu|gelu|relu)")
        self.activation = p.activation
        self.gated = p.gated
        self.out_shape = (b, s, e)
        std = 1.0 / math.sqrt(e)
        self.w1 = _declare_with_default(self, 0, "w1", (e, f), std, 1)
        self.w2 = _declare_with_default(self, 1, "w2", (f, e),
                                        1.0 / math.sqrt(f), 0)
        if self.gated:
            self.w3 = _declare_with_default(self, 2, "w3", (e, f), std, 1)

    def apply(self, params, srcs, ctx):
        x = srcs[0]

        def cast(w):
            return (w.astype(ctx.compute_dtype)
                    if ctx.compute_dtype is not None else w)
        act = {"silu": jax.nn.silu, "gelu": jax.nn.gelu,
               "relu": jax.nn.relu}[self.activation]
        h = jnp.einsum("bse,ef->bsf", x, cast(params[self.w1]),
                       preferred_element_type=jnp.float32).astype(x.dtype)
        h = act(h)
        if self.gated:
            g = jnp.einsum("bse,ef->bsf", x, cast(params[self.w3]),
                           preferred_element_type=jnp.float32).astype(x.dtype)
            h = h * g
        return jnp.einsum("bsf,fe->bse", h, cast(params[self.w2]),
                          preferred_element_type=jnp.float32).astype(x.dtype)


@register_layer("kMoE")
class MoELayer(Layer):
    """Mixture-of-experts FFN; expert-stacked weights shard over the
    "expert" mesh axis (partition_dim=0 on the stacked leading dim)."""

    is_loss = False

    def setup(self, src_shapes):
        p = self.cfg.moe_param
        if p is None:
            raise LayerError(f"{self.name}: moe_param required")
        b, s, e = tuple(src_shapes[0])
        self.n_exp = p.num_experts
        self.k = p.experts_per_token
        self.capacity_factor = p.capacity_factor
        self.aux_coef = p.router_aux_coef
        f = p.expert_hidden or 4 * e
        self.out_shape = (b, s, e)
        std = 1.0 / math.sqrt(e)
        self.router = _declare_with_default(self, 0, "router",
                                            (e, self.n_exp), std)
        self.w1 = _declare_with_default(self, 1, "w1",
                                        (self.n_exp, e, f), std, 0,
                                        mesh_axis="expert")
        self.b1 = _declare_with_default(self, 2, "b1", (self.n_exp, f),
                                        0.0, 0, mesh_axis="expert")
        self.w2 = _declare_with_default(self, 3, "w2",
                                        (self.n_exp, f, e),
                                        1.0 / math.sqrt(f), 0,
                                        mesh_axis="expert")
        self.b2 = _declare_with_default(self, 4, "b2", (self.n_exp, e),
                                        0.0, 0, mesh_axis="expert")
        self._aux = None

    def apply(self, params, srcs, ctx):
        x = srcs[0]
        p = {"router": params[self.router], "w1": params[self.w1],
             "b1": params[self.b1], "w2": params[self.w2],
             "b2": params[self.b2]}
        if ctx.compute_dtype is not None:
            p = {k: v.astype(ctx.compute_dtype) for k, v in p.items()}
        out, aux = moe_ops.moe_ffn(x, p, self.k, self.capacity_factor)
        # expose the router aux loss through a side metric dict entry
        self._aux = self.aux_coef * aux
        return out


@register_layer("kResidualAdd")
class ResidualAddLayer(Layer):
    """out = srcs[0] + srcs[1] — explicit residual edges in the DAG."""

    def setup(self, src_shapes):
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return srcs[0] + srcs[1]


class _HeadProjection:
    """Shared (E, V) projection for the LM head layers — the single
    definition of the tied-transpose + compute-dtype semantics, used by
    training (`apply`) and the KV-cache decode path (models/generate.py)
    alike."""

    def head_weight(self, params, compute_dtype=None):
        """(weight, is_vE): the raw (V, E) embedding table when tied —
        consumers contract E on the last dim (dot_general) instead of
        transposing, so no transposed copy of the table materializes
        (measured ~1-2 ms/step on the 32k-vocab bench stack, worse
        with an f32 master table)."""
        w = params[self.w_key]
        if compute_dtype is not None:
            w = w.astype(compute_dtype)
        return w, self.tied

    def project_logits(self, params, hidden, compute_dtype=None):
        """(B, S, E) hidden → (B, S, V) float32 logits."""
        w, is_vE = self.head_weight(params, compute_dtype)
        spec = "bse,ve->bsv" if is_vE else "bse,ev->bsv"
        return jnp.einsum(spec, hidden, w,
                          preferred_element_type=jnp.float32)


@register_layer("kLMHead")
class LMHeadLayer(Layer, _HeadProjection):
    """(B, S, E) → (B, S, V) logits; optionally tied to the embedding via
    share_param."""

    def setup(self, src_shapes):
        p = self.cfg.embed_param
        if p is None or not p.vocab_size:
            raise LayerError(f"{self.name}: embed_param.vocab_size required")
        b, s, e = tuple(src_shapes[0])
        self.out_shape = (b, s, p.vocab_size)
        # tied head: share_param aliases the (vocab, E) embedding, which
        # must be transposed at use — decided here from the config, not
        # from a shape heuristic (vocab == E would be ambiguous)
        self.tied = bool(self.cfg.share_param)
        self.w_key = _declare_with_default(
            self, 0, "w", (e, p.vocab_size), 1.0 / math.sqrt(e), 1)

    def apply(self, params, srcs, ctx):
        return self.project_logits(params, srcs[0], ctx.compute_dtype)


@register_layer("kLMHeadLoss")
class LMHeadLossLayer(Layer, _HeadProjection):
    """Fused LM head + softmax-xent + top-k precision: (B, S, E) hidden
    + (B, S) labels → metrics, WITHOUT materializing (B, S, V) logits
    (ops.loss.chunked_lm_xent: chunked scan, checkpointed recompute in
    the backward).  Numerically identical to kLMHead → kSoftmaxLoss; use
    this form for large vocabularies where the logits tensor would
    dominate HBM traffic."""

    is_loss = True

    def setup(self, src_shapes):
        p = self.cfg.embed_param
        if p is None or not p.vocab_size:
            raise LayerError(f"{self.name}: embed_param.vocab_size required")
        b, s, e = tuple(src_shapes[0])
        lp = self.cfg.softmaxloss_param
        self.topk = lp.topk if lp else 1
        self.scale = lp.scale if lp else 1.0
        self.chunk = p.loss_chunk or 4096
        self.tied = bool(self.cfg.share_param)
        self.w_key = _declare_with_default(
            self, 0, "w", (e, p.vocab_size), 1.0 / math.sqrt(e), 1)
        self.flops_shape = (b, s, e, p.vocab_size)   # for utils.flops
        self.out_shape = (2,)

    def _use_fused(self, h2, w, is_vE) -> bool:
        """Whether the fused Pallas forward applies: tied (V, E)
        layout, top-1 metric, kernel-legal shapes, real TPU."""
        from ..ops.attention import _on_tpu
        from ..ops.head_loss import eligible
        return (self.topk == 1 and is_vE and _on_tpu()
                and eligible(h2, w))

    @staticmethod
    def _shard_tokens(h2, l2, b, s, ctx):
        """Keep the flattened (B·S, ·) token dim sharded over
        ("data", "seq") jointly.  Without this constraint GSPMD resolves
        the (B, S, E)→(B·S, E) reshape under sequence parallelism by
        ALL-GATHERING the full sequence per data shard (observed in
        lowered HLO: an f32[B/dp, S, E] gather) — which defeats the
        O(S/n) activation memory SP exists for.  NOT free: the merged
        row order is b-major, so the ("data","seq") tiling differs from
        the source (b-block, s-block) tiles and GSPMD inserts an
        all-to-all reshard (visible in lowered HLO) costing O(local
        bytes) over ICI per step — the bounded price for never
        materializing full-S activations."""
        if ctx.mesh is None:
            return h2, l2
        from jax.sharding import NamedSharding, PartitionSpec as P
        shape = dict(ctx.mesh.shape)
        dp, sp = shape.get("data", 1), shape.get("seq", 1)
        if sp <= 1 or b % dp or s % sp:
            return h2, l2
        tok = P(("data", "seq"))
        h2 = jax.lax.with_sharding_constraint(
            h2, NamedSharding(ctx.mesh, P(("data", "seq"), None)))
        l2 = jax.lax.with_sharding_constraint(
            l2, NamedSharding(ctx.mesh, tok))
        return h2, l2

    def apply(self, params, srcs, ctx):
        from ..ops.head_loss import fused_lm_xent
        from ..ops.loss import chunked_lm_xent
        hidden, labels = srcs
        w, is_vE = self.head_weight(params, ctx.compute_dtype)
        b, s, e = hidden.shape
        h2, l2 = hidden.reshape(b * s, e), labels.reshape(-1)
        h2, l2 = self._shard_tokens(h2, l2, b, s, ctx)
        # fused Pallas forward (one pass over vocab blocks, logits
        # VMEM-only — ops/head_loss.py) for tied heads at kernel-legal
        # shapes; the chunked XLA path covers everything else
        if self._use_fused(h2, w, is_vE):
            loss, prec = fused_lm_xent(h2, w, l2, self.scale,
                                       self.chunk)
            return {"loss": loss, "precision": prec}
        loss, prec = chunked_lm_xent(
            h2, w, l2, chunk_size=self.chunk, topk=self.topk,
            scale=self.scale, w_is_vE=is_vE)
        return {"loss": loss, "precision": prec}
