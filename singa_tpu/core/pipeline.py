"""Closed-loop train-and-serve pipeline: one workspace, a supervised
trainer publishing into it, and a serving fleet promoting out of it —
the "online learning" loop the reference's train-only world never
answered (PAPER.md; TensorFlow's serving story, arxiv 1605.08695, is
exactly this checkpoint-publication loop).

`PipelineController` owns both halves and the seam between them:

    trainer ──save──▶ workspace ──fingerprint──▶ rollout ──▶ traffic
       ▲                 │                          │
       └── Supervisor    └── MANIFEST.json          └── canary →
           restart/rescue    health verdicts            promote/rollback

Publication state machine (one checkpoint's life):

    SAVED      Trainer._save_checkpoint wrote the snapshot + verdict
               (drain-before-save ⇒ drain-before-publish: every step
               the snapshot contains was classified first; a fatal
               window is REFUSED and never reaches disk)
    PUBLISHED  the `on_checkpoint` hook fired (`pipeline.publish`
               span/event, `pipeline.publish` fault site).  A verdict
               of ok/None makes the step BLESSED; a suspect (spike)
               save is published but NOT blessed — the rollout's
               manifest gate will reject it at the canary
    CANARIED   the fleet's RolloutController noticed the fingerprint
               change on its own poll (the publish hook is telemetry,
               not a command channel — losing it loses nothing) and
               reloaded exactly ONE engine
    PROMOTED / the canary verdict decides; ROLLBACK restores the
    ROLLED-BACK  canary to the pinned step (or to fresh-init params
               when nothing was ever promoted — `reload(step=-1)`)

The checkpoint-to-traffic lag gauge is the loop's health number:
`lag_steps` = last blessed step − fleet pinned (served) step, and
`lag_s` = seconds the oldest not-yet-served blessed step has been
waiting.  Both are 0 in steady state; a lag that only grows means the
loop is open (rollout dead, every canary rejected, or the fleet
wedged) — `spec.lag_alarm_s` logs it loudly.

Safety invariants (tested in tests/test_pipeline_mode.py, measured in
`bench.py --pipeline-smoke`):
  * a DIVERGED/NONFINITE window never reaches disk (save refused), a
    suspect one never passes the canary gate — so a bad step is never
    served by more than the canary, and traffic never regresses below
    the pinned step;
  * the trainer and the serving poll race safely: a mid-rename or
    half-written MANIFEST.json reads as "no change" (counted
    `torn_polls`), never an exception or a torn reload;
  * a trainer crash/preemption mid-pipeline is the Supervisor's
    problem and invisible to traffic — the fleet keeps serving the
    pinned step, and the restarted trainer's next blessed save
    re-enters the loop.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from .. import obs
from ..utils import faults


@dataclass(frozen=True)
class PipelineSpec:
    """`--pipeline_spec` grammar (RolloutSpec mold): comma/semicolon-
    separated `key=value`."""
    lag_alarm_s: float = 10.0   # blessed→served lag that logs an alarm
    join_s: float = 600.0       # default wait() budget for training
    seed: int = 0

    def __post_init__(self):
        if float(self.lag_alarm_s) <= 0:
            raise ValueError(f"lag_alarm_s must be > 0, got "
                             f"{self.lag_alarm_s}")
        if float(self.join_s) <= 0:
            raise ValueError(f"join_s must be > 0, got {self.join_s}")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "PipelineSpec":
        kw: Dict[str, Any] = {}
        types = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key, sep, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or key not in types:
                    raise ValueError(f"unknown key {key!r}")
                kw[key] = (float(val) if "float" in str(types[key])
                           else int(val))
            except ValueError as e:
                raise ValueError(f"bad pipeline spec entry {part!r} "
                                 f"(want key=value): {e}") from e
        return cls(**kw)


class PipelineController:
    """Owns a `Supervisor`-wrapped trainer (background thread) and an
    `EngineFleet` (with its rollout controller) against ONE workspace.
    See the module docstring for the publication state machine; the
    controller itself only *observes* the seam — the trainer's
    `on_checkpoint` hook records blessed steps for the lag gauge, and
    the rollout controller drives promotion off the checkpoint
    fingerprint entirely on its own, so neither half can wedge the
    other."""

    def __init__(self, supervisor, fleet, workspace: str,
                 spec: Optional[PipelineSpec] = None,
                 autoscale_spec=None,
                 log_fn: Optional[Callable[[str], None]] = None):
        if fleet.rollout is None:
            raise ValueError(
                "PipelineController needs a fleet built over the "
                "training workspace (EngineFleet(..., workspace=...)) "
                "— without a rollout controller no checkpoint would "
                "ever reach traffic")
        self.supervisor = supervisor
        self.fleet = fleet
        self.workspace = workspace
        self.spec = spec or PipelineSpec()
        self.log = log_fn or obs.get_logger("pipeline")
        # publication bookkeeping (all under the lock: the publish
        # hook runs on the trainer thread, lag()/snapshot() anywhere)
        self._lock = threading.Lock()
        self._blessed: Dict[int, float] = {}   # step -> publish time
        self.last_blessed_step: int = -1
        self.published = 0          # on_checkpoint firings (any verdict)
        self.unblessed = 0          # published with a non-ok verdict
        self.publish_faults = 0     # pipeline.publish site fired
        self.promote_lags_s: list = []  # blessed→served, seen at poll
        self._lag_alarmed: set = set()
        # trainer thread state
        self._thread: Optional[threading.Thread] = None
        self.train_result = None    # (params, opt_state, history)
        self.train_error: Optional[BaseException] = None
        self._train_done = threading.Event()
        # optional SLO-driven autoscaler: under pipeline mode the
        # blessed→served lag joins its pressure signals, so a fleet
        # too busy to promote is never shrunk
        self.autoscaler = None
        if autoscale_spec is not None:
            from ..serve.autoscale import AutoScaler
            self.autoscaler = AutoScaler(fleet, spec=autoscale_spec,
                                         lag_fn=self.lag,
                                         log_fn=self.log)
        supervisor.trainer.on_checkpoint = self._on_publish

    # -- lifecycle ----------------------------------------------------------
    def start(self, train_iter_factory, **run_kw) -> "PipelineController":
        """Serve first, then train: the fleet comes up on whatever the
        workspace already holds (fresh-init params at step -1 on a cold
        start), so traffic never waits on training; the trainer thread
        then runs `Supervisor.run(train_iter_factory, **run_kw)` to
        completion, publishing on its checkpoint cadence."""
        self.fleet.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        obs.emit_event("pipeline.start",
                       pinned=self.fleet.rollout.pinned_step,
                       engines=len(self.fleet.router.names()))
        self.log(f"pipeline: fleet up (pinned at step "
                 f"{self.fleet.rollout.pinned_step}); starting "
                 f"supervised training")
        self._train_done.clear()
        self._thread = threading.Thread(
            target=self._train, args=(train_iter_factory,),
            kwargs=run_kw, name="pipeline-train", daemon=True)
        self._thread.start()
        return self

    def _train(self, train_iter_factory, **run_kw) -> None:
        try:
            with obs.span("pipeline.train"):
                self.train_result = self.supervisor.run(
                    train_iter_factory, **run_kw)
        except BaseException as e:  # noqa: BLE001 — surfaced via wait()
            self.train_error = e
            self.log(f"pipeline: training FAILED "
                     f"({type(e).__name__}: {e}); the fleet keeps "
                     f"serving the last promoted step")
        finally:
            self._train_done.set()
            obs.emit_event("pipeline.train_done",
                           ok=self.train_error is None,
                           error=(repr(self.train_error)
                                  if self.train_error else None),
                           blessed_step=self.last_blessed_step)

    def train_running(self) -> bool:
        return self._thread is not None and not self._train_done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the trainer (default budget `spec.join_s`).  Returns
        True when training finished — check `train_error` for how.
        The fleet keeps serving either way; `stop()` is separate."""
        if self._thread is None:
            return True
        self._thread.join(self.spec.join_s if timeout is None
                          else timeout)
        return self._train_done.is_set()

    def stop(self) -> None:
        """Stop the serving half and detach the publish hook.  The
        trainer thread is not killable — callers size train_steps (or
        use wait()) so it has finished; a still-running trainer keeps
        checkpointing into the workspace harmlessly."""
        self.supervisor.trainer.on_checkpoint = None
        if self.train_running():
            self.log("warning: pipeline stopped while training still "
                     "runs; its checkpoints will land unserved")
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.fleet.stop()

    def __enter__(self) -> "PipelineController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the publication seam -----------------------------------------------
    def _on_publish(self, step: int, verdict) -> None:
        """Trainer post-save hook: record the publication and (verdict
        ok/None) bless the step for the lag gauge.  The `pipeline.
        publish` fault site degrades to a counted non-event — the
        rollout controller watches the fingerprint itself, so a lost
        notification never loses a promotion."""
        blessed = verdict in (None, "ok")
        with obs.span("pipeline.publish", step=step,
                      verdict=verdict, blessed=blessed):
            try:
                faults.maybe_fault("pipeline.publish")
            except Exception as e:  # noqa: BLE001 — degrade, count
                with self._lock:
                    self.publish_faults += 1
                self.log(f"warning: pipeline publish fault at step "
                         f"{step} ({type(e).__name__}: {e}); rollout "
                         f"will pick the checkpoint up on its own "
                         f"poll")
            with self._lock:
                self.published += 1
                if blessed:
                    self._blessed[step] = time.monotonic()
                    self.last_blessed_step = max(
                        self.last_blessed_step, step)
                else:
                    self.unblessed += 1
        obs.emit_event("pipeline.publish", step=step,
                       verdict=verdict, blessed=blessed,
                       served=self.fleet.rollout.pinned_step)
        if blessed:
            self.log(f"pipeline: published blessed checkpoint step "
                     f"{step} (serving step "
                     f"{self.fleet.rollout.pinned_step})")

    # -- the lag gauge ------------------------------------------------------
    def lag(self) -> Dict[str, Any]:
        """Checkpoint-to-traffic lag, the loop's health number:
        `lag_steps` = last blessed step − served (fleet-pinned) step
        (0 when nothing is waiting), `lag_s` = seconds the OLDEST
        unserved blessed step has waited.  Blessed steps the fleet has
        caught up past are pruned here, recording their observed
        blessed→served latency in `promote_lags_s`."""
        served = self.fleet.rollout.pinned_step
        now = time.monotonic()
        with self._lock:
            for s in sorted(k for k in self._blessed if k <= served):
                self.promote_lags_s.append(now - self._blessed.pop(s))
            waiting = {s: t for s, t in self._blessed.items()
                       if s > served}
            blessed = self.last_blessed_step
        lag_steps = max(blessed - served, 0) if blessed >= 0 else 0
        lag_s = (now - min(waiting.values())) if waiting else 0.0
        if lag_s > float(self.spec.lag_alarm_s) and \
                blessed not in self._lag_alarmed:
            self._lag_alarmed.add(blessed)
            self.log(f"warning: pipeline lag alarm — blessed step "
                     f"{blessed} unserved for {lag_s:.1f}s (fleet "
                     f"pinned at {served}); the loop may be open")
            obs.emit_event("pipeline.lag_alarm", blessed=blessed,
                           served=served, lag_s=round(lag_s, 3))
        return {"blessed_step": blessed, "served_step": served,
                "lag_steps": lag_steps, "lag_s": round(lag_s, 3)}

    def register_into(self, registry,
                      prefix: str = "singa_pipeline") -> None:
        """Expose the loop through an `obs.MetricsRegistry` collector
        (/metrics): the lag pair as gauges, publications as
        counters."""
        from ..obs.metrics import Sample

        def collect():
            lag = self.lag()
            with self._lock:
                pub, unb, flt = (self.published, self.unblessed,
                                 self.publish_faults)
            return [
                Sample(f"{prefix}_blessed_step", "gauge",
                       "last health-blessed checkpoint step",
                       float(lag["blessed_step"])),
                Sample(f"{prefix}_served_step", "gauge",
                       "fleet-pinned (promoted) checkpoint step",
                       float(lag["served_step"])),
                Sample(f"{prefix}_lag_steps", "gauge",
                       "blessed minus served step",
                       float(lag["lag_steps"])),
                Sample(f"{prefix}_lag_seconds", "gauge",
                       "age of the oldest unserved blessed step",
                       float(lag["lag_s"])),
                Sample(f"{prefix}_published_total", "counter",
                       "checkpoint publications (any verdict)",
                       float(pub)),
                Sample(f"{prefix}_unblessed_total", "counter",
                       "publications with a non-ok verdict",
                       float(unb)),
                Sample(f"{prefix}_publish_faults_total", "counter",
                       "injected/real publish-hook faults survived",
                       float(flt)),
            ]

        registry.register_collector(collect)
        if self.autoscaler is not None:
            self.autoscaler.register_into(registry)

    # -- client passthrough + snapshot --------------------------------------
    def generate(self, tokens, timeout=None) -> Dict[str, Any]:
        return self.fleet.generate(tokens, timeout=timeout)

    def predict(self, tokens, timeout=None) -> Dict[str, Any]:
        return self.fleet.predict(tokens, timeout=timeout)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view: the lag pair, publication counters, the
        trainer's supervision state, and the whole fleet snapshot."""
        lag = self.lag()
        with self._lock:
            out: Dict[str, Any] = {
                **lag,
                "published": self.published,
                "unblessed": self.unblessed,
                "publish_faults": self.publish_faults,
                "promote_lag_max_s": (round(max(self.promote_lags_s), 3)
                                      if self.promote_lags_s else None),
            }
        out["train"] = {
            "running": self.train_running(),
            "done": self._train_done.is_set(),
            "error": (repr(self.train_error) if self.train_error
                      else None),
            "failures": len(self.supervisor.failures),
        }
        out["fleet"] = self.fleet.snapshot()
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.snapshot()
        return out
