"""The layer zoo: reference layer types as pure JAX compute.

Each reference Layer subclass (base_layer.h:38-563, layer.h:28-291) maps
to a registry entry here with three duties:

  setup(src_shapes)   — shape inference + param spec declaration
                        (reference Layer::Setup)
  apply(params, srcs, ctx) — forward compute (reference ComputeFeature);
                        the backward (ComputeGradient) comes from jax.grad.

The whole net therefore compiles to one XLA program per phase instead of
a hand-scheduled per-layer interpreter loop.

Layer `type` strings are the reference's registry keys
(neuralnet.cc:13-44): kConvolution, kPooling, kLRN, kInnerProduct,
kReLU, kTanh, kSigmoid, kDropout, kSoftmaxLoss, kMnistImage, kRGBImage,
kLabel, kShardData, kLMDBData, kConcate, kSlice, kSplit, kBridgeSrc,
kBridgeDst — plus TPU-native modern types (kEmbed, kAttention, kRMSNorm,
kMoE, kRBM) registered by their model families.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import ops
from ..config.schema import LayerConfig, ParamConfig


class LayerError(ValueError):
    pass


@dataclass
class ParamSpec:
    name: str           # global key: "<layer>/<param-name>"
    shape: Tuple[int, ...]
    fan_in: int
    cfg: ParamConfig
    # sharding hint: ParamProto.partition_dim (-1 = replicate)
    partition_dim: int = -1
    # mesh axis the partition_dim shards over; None = the TP axis
    # ("model"). MoE expert-stacked params use "expert".
    mesh_axis: Optional[str] = None


@dataclass
class Context:
    """Per-call state threaded through Layer.apply."""
    batch: Dict[str, Any]
    train: bool
    rng: Optional[jax.Array] = None
    layer_index: int = 0
    mesh: Any = None            # jax.sharding.Mesh for SP/EP-aware layers
    compute_dtype: Any = None   # e.g. jnp.bfloat16 under ModelProto.precision
    step: Any = None            # traced global step (cadence-aware layers,
    #                             e.g. MnistProto.elastic_freq)

    def layer_rng(self) -> jax.Array:
        if self.rng is None:
            raise LayerError("layer needs an rng but none was provided")
        return jax.random.fold_in(self.rng, self.layer_index)


LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(type_name: str):
    def deco(cls):
        LAYER_REGISTRY[type_name] = cls
        cls.type_name = type_name
        return cls
    return deco


class Layer:
    """Base layer. Subclasses fill out_shape and param_specs in setup()."""

    is_data = False     # True → reads from ctx.batch, has no srcs
    is_loss = False     # True → apply returns a metrics dict incl. "loss"

    def __init__(self, cfg: LayerConfig):
        self.cfg = cfg
        self.name = cfg.name
        self.out_shape: Any = None
        self.param_specs: List[ParamSpec] = []

    def setup(self, src_shapes: List[Any]) -> None:
        raise NotImplementedError

    def apply(self, params: Dict[str, jnp.ndarray], srcs: List[Any],
              ctx: Context) -> Any:
        raise NotImplementedError

    # -- helpers ----------------------------------------------------------
    def _param_cfg(self, i: int, default_name: str) -> ParamConfig:
        if i < len(self.cfg.param):
            return self.cfg.param[i]
        return ParamConfig(name=default_name)

    def _declare(self, i: int, default_name: str, shape, fan_in: int,
                 partition_dim: int = -1) -> str:
        pcfg = self._param_cfg(i, default_name)
        pname = pcfg.name or default_name
        key = f"{self.name}/{pname}"
        if pcfg.partition_dim != -1:
            partition_dim = pcfg.partition_dim
        self.param_specs.append(
            ParamSpec(key, tuple(shape), fan_in, pcfg, partition_dim))
        return key


# ---------------------------------------------------------------------------
# data / parser layers


@register_layer("kShardData")
class ShardDataLayer(Layer):
    """Input layer (layer.cc:646-673): emits the raw record batch provided
    by the host input pipeline via ctx.batch[self.name]."""

    is_data = True

    def setup(self, src_shapes, sample_shapes: Optional[Dict] = None):
        bs = self.cfg.data_param.batchsize if self.cfg.data_param else 0
        self.batchsize = bs
        self.sample_shapes = sample_shapes or {}
        self.out_shape = {k: (bs,) + tuple(v)
                          for k, v in self.sample_shapes.items()}

    def apply(self, params, srcs, ctx):
        try:
            return ctx.batch[self.name]
        except KeyError:
            raise LayerError(
                f"batch missing entry for data layer {self.name!r}; "
                f"have {list(ctx.batch)}")


@register_layer("kLMDBData")
class LMDBDataLayer(ShardDataLayer):
    """LMDB-backed data layer (layer.cc:237-328). Device-side it is
    identical to ShardData: the host pipeline supplies the batch."""


@register_layer("kMnistImage")
class MnistImageLayer(Layer):
    """Parser (layer.cc:380-473): uint8 pixels → (x/norm_a - norm_b),
    output (B, s, s).  The reference does this per-pixel on the host; here
    it runs inside the jitted step (zero CPU in the inner loop).

    The elastic-distortion surface the reference declares but left
    commented out (MnistProto kernel/sigma/alpha/beta/gamma,
    model.proto:211-225) is implemented on-device (ops/augment.py) and
    applied in the training phase when any strength is nonzero.
    `elastic_freq` gates it to every freq-th step (the field the
    reference reads in Setup, layer.cc:462, for exactly that cadence);
    `resize` rescales samples to (resize, resize) (layer.cc:466-467
    reshapes the output blob to that size)."""

    def setup(self, src_shapes):
        p = self.cfg.mnist_param
        self.norm_a = p.norm_a if p else 1.0
        self.norm_b = p.norm_b if p else 0.0
        self.distort = dict(
            kernel=p.kernel, sigma=p.sigma, alpha=p.alpha,
            beta=p.beta, gamma=p.gamma) if p else {}
        self.distort_on = bool(p and (
            (p.alpha > 0 and p.kernel > 0) or p.beta > 0 or p.gamma > 0))
        self.elastic_freq = p.elastic_freq if p else 0
        self.resize = p.resize if p else 0
        pix = tuple(src_shapes[0]["pixel"])
        if self.resize:
            pix = pix[:1] + (self.resize, self.resize) + pix[3:]
        self.out_shape = pix

    def apply(self, params, srcs, ctx):
        x = srcs[0]["pixel"].astype(jnp.float32)
        if self.resize and x.shape[1:3] != (self.resize, self.resize):
            x = jax.image.resize(
                x, (x.shape[0], self.resize, self.resize) + x.shape[3:],
                method="bilinear")
        if self.distort_on and ctx.train:
            from ..ops.augment import elastic_deform
            rng = ctx.layer_rng()
            if self.elastic_freq > 1 and ctx.step is not None:
                # distort only every elastic_freq-th step (layer.cc:462);
                # lax.cond skips the displacement-field work entirely on
                # off steps (jnp.where would compute-and-discard it)
                on = (jnp.asarray(ctx.step) % self.elastic_freq) == 0
                x = jax.lax.cond(
                    on,
                    lambda t: elastic_deform(t, rng, **self.distort),
                    lambda t: t, x)
            else:
                x = elastic_deform(x, rng, **self.distort)
        x = x / self.norm_a - self.norm_b
        if ctx.compute_dtype is not None:
            x = x.astype(ctx.compute_dtype)
        return x


@register_layer("kRGBImage")
class RGBImageLayer(Layer):
    """Parser (layer.cc:571-643): mean-subtract, random crop + mirror in
    training / center crop in eval, scale.

    Host batches arrive channels-first ((B, 3, H, W), the Record pixel
    layout); the parser transposes once to NHWC — the layout the whole
    vision stack runs in on TPU (channels on the 128-lane axis; see
    ops/conv.py).  Output (B, crop, crop, 3)."""

    def setup(self, src_shapes):
        p = self.cfg.rgbimage_param
        self.scale = p.scale if p else 1.0
        self.cropsize = p.cropsize if p else 0
        self.mirror = bool(p.mirror) if p else False
        self.mean = (self._load_mean(p.meanfile)
                     if p and p.meanfile else None)
        b, c, h, w = src_shapes[0]["pixel"]  # (B, C, H, W) host layout
        if self.cropsize:
            h = w = self.cropsize
        self.out_shape = (b, h, w, c)

    @staticmethod
    def _load_mean(path: str):
        """Per-pixel mean record (the mean.binaryproto role,
        layer.cc:579-583: ReadProtoFromBinaryFile + mean subtract).
        Written by tools/loader.py compute_mean; fails loudly when the
        configured file is missing or malformed."""
        import numpy as _np

        from ..data.records import Record
        try:
            with open(path, "rb") as f:
                rec = Record.decode(f.read())
            arr = _np.asarray(rec.image.data, _np.float32).reshape(
                tuple(rec.image.shape))
        except FileNotFoundError:
            raise LayerError(
                f"rgbimage_param.meanfile {path!r} does not exist — "
                f"build it with singa_tpu.tools.loader compute_mean")
        except Exception as e:
            raise LayerError(
                f"rgbimage_param.meanfile {path!r} is not a mean "
                f"record: {e}")
        return arr

    def apply(self, params, srcs, ctx):
        x = srcs[0]["pixel"].astype(jnp.float32)
        # batch-supplied mean (pipeline) wins over the configured file
        mean = srcs[0].get("mean")
        if mean is None and self.mean is not None:
            mean = jnp.asarray(self.mean)
        if mean is not None:
            x = x - mean
        x = x.transpose(0, 2, 3, 1)  # → NHWC
        b, h, w, c = x.shape
        cs = self.cropsize
        # Per-IMAGE augmentation randomness, as the reference draws it
        # inside its per-record parse loop (layer.cc:587-616:
        # hoff=rand()%(shape-cropsize) and do_mirror=mirror_&&rand()%2
        # for every record).  Batch-correlated crops/flips are
        # measurably weaker regularization.  Two deliberate deviations
        # from the reference's literal code: (a) it re-rolls the mirror
        # coin outside the `training` guard (layer.cc:613), mirroring
        # at test time — here mirror is train-only; (b) at test time
        # with a cropsize it memcpys the full record into the smaller
        # cropped blob (layer.cc:596-602) — here eval takes the
        # conventional center crop.
        rng = (ctx.layer_rng()
               if ctx.train and (self.mirror or
                                 (cs and (h > cs or w > cs)))
               else None)
        if cs and (h > cs or w > cs):
            if ctx.train:
                r1, r2, rng = jax.random.split(rng, 3)
                oh = jax.random.randint(r1, (b,), 0, max(h - cs, 1))
                ow = jax.random.randint(r2, (b,), 0, max(w - cs, 1))
                x = jax.vmap(
                    lambda img, i, j: jax.lax.dynamic_slice(
                        img, (i, j, 0), (cs, cs, c)))(x, oh, ow)
            else:
                oh, ow = (h - cs) // 2, (w - cs) // 2
                x = x[:, oh:oh + cs, ow:ow + cs]
        if self.mirror and ctx.train:
            flip = jax.random.bernoulli(rng, shape=(b,))
            x = jnp.where(flip[:, None, None, None], x[:, :, ::-1], x)
        x = x * self.scale
        if ctx.compute_dtype is not None:
            x = x.astype(ctx.compute_dtype)
        return x


@register_layer("kLabel")
class LabelLayer(Layer):
    """Parser (layer.cc:416-432): int labels, shape (B,)."""

    def setup(self, src_shapes):
        self.out_shape = tuple(src_shapes[0]["label"])

    def apply(self, params, srcs, ctx):
        return srcs[0]["label"]


# ---------------------------------------------------------------------------
# neuron layers


def _nhwc_shape(shape):
    """Vision activations run NHWC on TPU.  Reference conv/pool accept
    3-D (B,H,W) inputs as single-channel (layer.cc:31-36) → (B,H,W,1)."""
    if len(shape) == 3:
        return (shape[0], shape[1], shape[2], 1)
    return tuple(shape)


def _as_nhwc(x):
    if x.ndim == 3:
        return x.reshape(x.shape[0], x.shape[1], x.shape[2], 1)
    return x


@register_layer("kConvolution")
class ConvolutionLayer(Layer):
    """layer.cc:26-123. Weight kept in the reference layout
    (num_filters, C*k*k); compute is one lax.conv_general_dilated."""

    def setup(self, src_shapes):
        p = self.cfg.convolution_param
        if p is None or not p.kernel:
            raise LayerError(f"{self.name}: convolution_param.kernel required")
        b, h, w, c = _nhwc_shape(src_shapes[0])
        self.channels, self.height, self.width = c, h, w
        self.kernel, self.stride, self.pad = p.kernel, p.stride, p.pad
        self.num_filters = p.num_filters
        self.bias_term = p.bias_term
        ch = ops.conv_out_size(h, p.kernel, p.stride, p.pad)
        cw = ops.conv_out_size(w, p.kernel, p.stride, p.pad)
        self.out_shape = (b, ch, cw, p.num_filters)
        col_height = c * p.kernel * p.kernel
        self.w_key = self._declare(0, "weight", (p.num_filters, col_height),
                                   fan_in=col_height, partition_dim=0)
        if self.bias_term:
            self.b_key = self._declare(1, "bias", (p.num_filters,), fan_in=0,
                                       partition_dim=0)

    def apply(self, params, srcs, ctx):
        x = _as_nhwc(srcs[0])
        bias = params[self.b_key] if self.bias_term else None
        return ops.conv2d(x, params[self.w_key], bias, kernel=self.kernel,
                          stride=self.stride, pad=self.pad,
                          channels=self.channels, layout="NHWC")


@register_layer("kPooling")
class PoolingLayer(Layer):
    def setup(self, src_shapes):
        p = self.cfg.pooling_param
        if p is None or not p.kernel:
            raise LayerError(f"{self.name}: pooling_param.kernel required")
        if p.pool not in ("MAX", "AVE"):
            raise LayerError(f"{self.name}: bad pool method {p.pool!r}")
        b, h, w, c = _nhwc_shape(src_shapes[0])
        self.kernel, self.stride, self.mode = p.kernel, p.stride, p.pool
        self.out_shape = (b, ops.pooled_size(h, p.kernel, p.stride),
                          ops.pooled_size(w, p.kernel, p.stride), c)

    def apply(self, params, srcs, ctx):
        x = _as_nhwc(srcs[0])
        if self.mode == "MAX":
            return ops.max_pool2d(x, self.kernel, self.stride, layout="NHWC")
        return ops.avg_pool2d(x, self.kernel, self.stride, layout="NHWC")


@register_layer("kLRN")
class LRNLayer(Layer):
    """`fuse_from`: set by NeuralNet when this LRN's source is a plain
    ReLU — apply() then receives the *pre-relu* tensor and runs the
    fused relu+lrn custom_vjp (ops/lrn.py), never materializing the
    relu output on the train path (any other consumers of the relu
    still get it from the ReLU layer; XLA dead-code-eliminates it when
    unused)."""

    fuse_from: str = ""

    def setup(self, src_shapes):
        p = self.cfg.lrn_param
        self.local_size = p.local_size if p else 5
        if self.local_size % 2 != 1:
            raise LayerError(f"{self.name}: LRN local_size must be odd")
        self.alpha = p.alpha if p else 1.0
        self.beta = p.beta if p else 0.75
        self.knorm = p.knorm if p else 1.0
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return ops.relu_lrn(srcs[0], self.local_size, self.alpha, self.beta,
                            self.knorm, relu=bool(self.fuse_from),
                            layout="NHWC")


@register_layer("kInnerProduct")
class InnerProductLayer(Layer):
    """layer.cc:162-213: flatten to (B, vdim), weight (vdim, hdim).
    NOTE the reference passes fan_in = vdim*hdim to Param::Setup
    (layer.cc:174) — reproduced for init parity.  vdim element order
    follows the NHWC runtime layout (H, W, C) rather than the
    reference's (C, H, W); weight shape and numerics are unaffected."""

    def setup(self, src_shapes):
        p = self.cfg.inner_product_param
        if p is None or not p.num_output:
            raise LayerError(f"{self.name}: inner_product_param.num_output "
                             "required")
        s = tuple(src_shapes[0])
        b = s[0]
        vdim = int(math.prod(s[1:]))
        hdim = p.num_output
        self.bias_term = p.bias_term
        self.out_shape = (b, hdim)
        self.w_key = self._declare(0, "weight", (vdim, hdim),
                                   fan_in=vdim * hdim, partition_dim=1)
        if self.bias_term:
            self.b_key = self._declare(1, "bias", (hdim,), fan_in=0,
                                       partition_dim=0)

    def apply(self, params, srcs, ctx):
        bias = params[self.b_key] if self.bias_term else None
        return ops.linear(srcs[0], params[self.w_key], bias)


@register_layer("kReLU")
class ReLULayer(Layer):
    def setup(self, src_shapes):
        self.slope = (self.cfg.relu_param.negative_slope
                      if self.cfg.relu_param else 0.0)
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return ops.relu(srcs[0], self.slope)


@register_layer("kTanh")
class TanhLayer(Layer):
    """Reference kTanh is the *scaled* tanh stanh (layer.cc:688-701) with
    hard-coded constants; TanhProto outer/inner_scale override them."""

    def setup(self, src_shapes):
        p = self.cfg.tanh_param
        if p is not None:
            self.outer, self.inner = p.outer_scale, p.inner_scale
        else:
            self.outer, self.inner = ops.activations.STANH_OUTER, \
                ops.activations.STANH_INNER
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return ops.stanh(srcs[0], self.outer, self.inner)


@register_layer("kSigmoid")
class SigmoidLayer(Layer):
    def setup(self, src_shapes):
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return ops.sigmoid(srcs[0])


@register_layer("kDropout")
class DropoutLayer(Layer):
    def setup(self, src_shapes):
        self.rate = (self.cfg.dropout_param.dropout_ratio
                     if self.cfg.dropout_param else 0.5)
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        if not ctx.train:
            return srcs[0]
        return ops.dropout(srcs[0], self.rate, ctx.layer_rng(), train=True)


# ---------------------------------------------------------------------------
# loss layers


@register_layer("kSoftmaxLoss")
class SoftmaxLossLayer(Layer):
    """layer.cc:702-765: fused softmax + NLL + top-k precision.
    srcs = [logits, label]."""

    is_loss = True

    def setup(self, src_shapes):
        p = self.cfg.softmaxloss_param
        self.topk = p.topk if p else 1
        self.scale = p.scale if p else 1.0
        self.out_shape = (2,)   # metric blob layout [loss, precision]

    def apply(self, params, srcs, ctx):
        logits, labels = srcs
        if labels.ndim > 1:
            # sequence labels (B, S): flatten to (B*S, V) token-level NLL
            logits = logits.reshape(-1, logits.shape[-1])
            labels = labels.reshape(-1)
        loss, prec = ops.softmax_loss_metrics(
            logits.astype(jnp.float32), labels, self.topk, self.scale)
        return {"loss": loss, "precision": prec}


# ---------------------------------------------------------------------------
# connector layers (partition infrastructure, base_layer.h:264-330 +
# base_layer.cc:39-194). Under GSPMD these are mostly identities or plain
# jnp ops — data movement is compiled in from sharding annotations.


@register_layer("kConcate")
class ConcateLayer(Layer):
    def setup(self, src_shapes):
        dim = (self.cfg.concate_param.concate_dimension
               if self.cfg.concate_param else 0)
        self.dim = dim
        shape = list(src_shapes[0])
        shape[dim] = sum(s[dim] for s in src_shapes)
        self.out_shape = tuple(shape)

    def apply(self, params, srcs, ctx):
        return jnp.concatenate(srcs, axis=self.dim)


@register_layer("kSlice")
class SliceLayer(Layer):
    """Scatter along slice_dimension into slice_num views; consumer i
    reads view i (base_layer.cc:114-173). Output is the tuple of views."""

    def setup(self, src_shapes):
        p = self.cfg.slice_param
        self.dim = p.slice_dimension if p else 0
        self.num = p.slice_num if p else 1
        s = list(src_shapes[0])
        base, rem = divmod(s[self.dim], self.num)
        shapes = []
        for i in range(self.num):
            # reference gives the remainder to the last partition
            # (neuralnet.cc:160-162 semantics)
            sz = base + (rem if i == self.num - 1 else 0)
            t = list(s)
            t[self.dim] = sz
            shapes.append(tuple(t))
        self.out_shape = tuple(shapes)

    def apply(self, params, srcs, ctx):
        x = srcs[0]
        base = x.shape[self.dim] // self.num
        outs = []
        start = 0
        for i in range(self.num):
            sz = (x.shape[self.dim] - start if i == self.num - 1 else base)
            idx = [slice(None)] * x.ndim
            idx[self.dim] = slice(start, start + sz)
            outs.append(x[tuple(idx)])
            start += sz
        return tuple(outs)


@register_layer("kSplit")
class SplitLayer(Layer):
    """Replicate to multiple consumers (base_layer.h:316-330) — a pure
    identity under functional semantics."""

    def setup(self, src_shapes):
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return srcs[0]


@register_layer("kBridgeSrc")
class BridgeSrcLayer(Layer):
    """Cross-location activation sender (base_layer.h:264-312). Under
    GSPMD the transfer is a compiled collective; the layer is an identity
    marker kept for config parity."""

    def setup(self, src_shapes):
        self.out_shape = tuple(src_shapes[0])

    def apply(self, params, srcs, ctx):
        return srcs[0]


@register_layer("kBridgeDst")
class BridgeDstLayer(BridgeSrcLayer):
    pass


def create_layer(cfg: LayerConfig) -> Layer:
    if cfg.type not in LAYER_REGISTRY:
        # the sequence family registers on import and is kept lazy
        # (it pulls in Pallas); load it on first unknown type.  kRBM
        # registers the same way from its model family.
        from . import seq_layers  # noqa: F401
        from ..models.rbm import register_rbm_layer
        register_rbm_layer()
    if cfg.type not in LAYER_REGISTRY:
        raise LayerError(f"unknown layer type {cfg.type!r} "
                         f"(registered: {sorted(LAYER_REGISTRY)})")
    return LAYER_REGISTRY[cfg.type](cfg)
