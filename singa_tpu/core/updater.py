"""Updaters (SGD-family optimizers) + learning-rate schedules.

Reference: /root/reference/src/utils/updater.cc.  Formula parity notes:

- LR schedules (updater.cc:11-51): kFixed, kLinear, kExponential,
  kInverse_t, kInverse, kStep.  kStep uses C++ *integer* division
  step/freq; kLinear/kExponential use float division.
- SGDUpdater (updater.cc:62-79): wd folded into grad, then
  history = momentum*history + lr*grad; data -= history (or plain
  data -= lr*grad when momentum == 0).
- NesterovUpdater (:89-105): data -= (1+mu)*h_new - mu*h_old.
- AdaGrad (:115-128): history += (grad*grad_scale)^2 BEFORE the wd fold;
  data -= lr*(grad + wd*data)/sqrt(history + delta).
- RMSProp (:140-153): history = rho*history + (1-rho)*(grad*scale)^2,
  same wd placement as AdaGrad.
- AdaDelta (:163-182): wd folded first; no lr (schedule unused);
  tmp = grad*sqrt(update+delta)/sqrt(history+delta).

All state (history/update) is zero-initialized, which reproduces the
reference's `if(step==0) history=0` reset.  Per-param
learning_rate_multiplier / weight_decay_multiplier come from
ParamProto (model.proto:103-105).

The whole update is pure pytree math — it runs inside the jitted train
step, fused by XLA into the backward pass (the TPU-native replacement
for the reference's ParamManager update loop, param_manager.cc:160-199).

TPU-native additions: kAdam, kCosine / kWarmupCosine schedules.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config.schema import UpdaterConfig


def learning_rate(cfg: UpdaterConfig, step) -> jnp.ndarray:
    """GetLearningRate (updater.cc:11-51), jit-traceable in `step`."""
    base = cfg.base_learning_rate
    method = cfg.learning_rate_change_method
    step = jnp.asarray(step, jnp.float32)
    if method == "kFixed":
        return jnp.asarray(base, jnp.float32)
    if method == "kLinear":
        r = step / cfg.learning_rate_change_frequency
        return (1.0 - r) * base + r * cfg.final_learning_rate
    if method == "kExponential":
        return base / jnp.power(2.0, step / cfg.learning_rate_change_frequency)
    if method == "kInverse_t":
        return base / (1.0 + step / cfg.final_learning_rate)
    if method == "kInverse":
        return base * jnp.power(1.0 + cfg.gamma * step, -cfg.pow)
    if method == "kStep":
        # C++ integer division step/freq (updater.cc:41-45)
        return base * jnp.power(
            cfg.gamma, jnp.floor(step / cfg.learning_rate_change_frequency))
    if method == "kCosine":
        t = jnp.clip(step / max(cfg.learning_rate_change_frequency, 1), 0, 1)
        return cfg.final_learning_rate + 0.5 * (base - cfg.final_learning_rate) * (
            1.0 + jnp.cos(jnp.pi * t))
    if method == "kWarmupCosine":
        warm = max(cfg.warmup_steps, 1)
        total = max(cfg.learning_rate_change_frequency, warm + 1)
        t = jnp.clip((step - warm) / (total - warm), 0, 1)
        cos_lr = cfg.final_learning_rate + 0.5 * (
            base - cfg.final_learning_rate) * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(step < warm, base * (step + 1) / warm, cos_lr)
    raise ValueError(f"unknown LR schedule {method!r}")


class Multipliers(NamedTuple):
    """Per-param static multipliers (ParamProto lr/wd multipliers)."""
    lr: float = 1.0
    wd: float = 1.0


class Updater:
    """Functional updater over a param pytree.

    state = self.init(params); params, state = self.update(step, grads,
    params, state).  `multipliers` is a pytree matching `params` whose
    leaves are `Multipliers` (defaults to all-ones).
    """

    def __init__(self, cfg: UpdaterConfig):
        self.cfg = cfg
        self.type = cfg.type
        # rescue-policy LR scale (Trainer.apply_lr_backoff): read at
        # trace time, so changing it requires rebuilding the jitted
        # steps; 1.0 leaves the traced program untouched
        self.lr_scale = 1.0
        # default-Multipliers pytrees, keyed by param treedef: built
        # ONCE (at init / first update) instead of on every traced
        # update call — the update runs inside the scan body, so every
        # per-call tree rebuild was paid per trace and inflated the
        # jaxpr's construction cost
        self._default_mults: Dict[Any, Any] = {}

    def _default_multipliers(self, treedef):
        tree = self._default_mults.get(treedef)
        if tree is None:
            tree = jax.tree_util.tree_unflatten(
                treedef, [Multipliers()] * treedef.num_leaves)
            self._default_mults[treedef] = tree
        return tree

    # -- state ------------------------------------------------------------
    def init(self, params) -> Dict[str, Any]:
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        state: Dict[str, Any] = {"history": zeros}
        if self.type in ("kAdaDelta", "kAdam"):
            state["update"] = jax.tree_util.tree_map(jnp.zeros_like, params)
        # hoist: pre-build the default multiplier tree for this param
        # structure so no update call ever constructs it
        self._default_multipliers(jax.tree_util.tree_structure(params))
        return state

    # -- update -----------------------------------------------------------
    def update(self, step, grads, params, state,
               multipliers=None, grad_scale: float = 1.0):
        cfg = self.cfg
        # one flatten pass yields leaves AND treedef; the other trees
        # reuse the treedef (flatten_up_to) instead of re-deriving it
        p_l, treedef = jax.tree_util.tree_flatten(params)
        if multipliers is None:
            multipliers = self._default_multipliers(treedef)
        lr = learning_rate(cfg, step) if cfg.base_learning_rate else 0.0
        if self.lr_scale != 1.0:
            lr = lr * self.lr_scale

        g_l = treedef.flatten_up_to(grads)
        m_l = jax.tree_util.tree_leaves(
            multipliers, is_leaf=lambda x: isinstance(x, Multipliers))
        h_l = treedef.flatten_up_to(state["history"])
        u_l = (treedef.flatten_up_to(state["update"])
               if "update" in state else [None] * len(p_l))

        new_p, new_h, new_u = [], [], []
        for p, g, h, u, m in zip(p_l, g_l, h_l, u_l, m_l):
            plr = lr * m.lr
            pwd = cfg.weight_decay * m.wd
            np_, nh, nu = self._apply_one(step, p, g, h, u, plr, pwd,
                                          grad_scale)
            new_p.append(np_)
            new_h.append(nh)
            new_u.append(nu)

        new_state = {"history": jax.tree_util.tree_unflatten(treedef, new_h)}
        if "update" in state:
            new_state["update"] = jax.tree_util.tree_unflatten(treedef, new_u)
        return jax.tree_util.tree_unflatten(treedef, new_p), new_state

    def _apply_one(self, step, p, g, h, u, lr, wd, grad_scale):
        cfg = self.cfg
        t = self.type
        if t == "kSGD":
            if wd > 0:
                g = g + p * wd
            if cfg.momentum > 0:
                h = h * cfg.momentum + lr * g
                return p - h, h, u
            return p - lr * g, h, u
        if t == "kNesterov":
            if wd > 0:
                g = g + p * wd
            h_old = h
            h = h * cfg.momentum + lr * g
            return p - (h * (1 + cfg.momentum) - h_old * cfg.momentum), h, u
        if t == "kAdaGrad":
            h = h + jnp.square(g * grad_scale)
            if wd > 0:
                g = g + p * wd
            return p - lr * g / jnp.sqrt(h + cfg.delta), h, u
        if t == "kRMSProp":
            h = h * cfg.rho + (1 - cfg.rho) * jnp.square(g * grad_scale)
            if wd > 0:
                g = g + p * wd
            return p - lr * g / jnp.sqrt(h + cfg.delta), h, u
        if t == "kAdaDelta":
            if wd > 0:
                g = g + p * wd
            h = h * cfg.rho + (1 - cfg.rho) * jnp.square(g * grad_scale)
            tmp = g * jnp.sqrt(u + cfg.delta) / jnp.sqrt(h + cfg.delta)
            u = cfg.rho * u + (1 - cfg.rho) * jnp.square(tmp)
            return p - tmp, h, u
        if t == "kAdam":
            if wd > 0:
                g = g + p * wd
            b1, b2 = cfg.beta1, cfg.beta2
            h = b1 * h + (1 - b1) * g          # first moment
            u = b2 * u + (1 - b2) * jnp.square(g)  # second moment
            tstep = jnp.asarray(step, jnp.float32) + 1.0
            mhat = h / (1 - b1 ** tstep)
            vhat = u / (1 - b2 ** tstep)
            return p - lr * mhat / (jnp.sqrt(vhat) + cfg.delta), h, u
        raise ValueError(f"unknown updater type {t!r}")


def make_updater(cfg: Optional[UpdaterConfig]) -> Updater:
    return Updater(cfg if cfg is not None else UpdaterConfig(type="kSGD"))
