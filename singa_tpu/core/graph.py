"""Generic named-node DAG with topological sort and JSON dump.

Capability parity with the reference graph utility
(/root/reference/include/utils/graph.h, src/utils/graph.cc): named nodes,
DFS topological sort (graph.cc:66-101), and a node-link JSON dump for
visualization (graph.cc:4-59).  The reference's mutation helpers
(InsertSliceNode/InsertConcateNode/InsertSplitNode/InsertBridgeNode,
graph.cc:105-146) exist there to rewrite the layer graph for partitioned
execution; in the TPU build that role is played by sharding annotations
(see singa_tpu.parallel.partition), so here the graph stays a pure
dependency structure.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence


class GraphError(ValueError):
    pass


class Graph:
    def __init__(self):
        self._edges: Dict[str, List[str]] = {}   # node -> dst list
        self._nodes: List[str] = []              # insertion order
        self._attrs: Dict[str, dict] = {}

    def add_node(self, name: str, **attrs) -> None:
        if name in self._edges:
            raise GraphError(f"duplicate node {name!r}")
        self._edges[name] = []
        self._nodes.append(name)
        self._attrs[name] = attrs

    def add_edge(self, src: str, dst: str) -> None:
        for n in (src, dst):
            if n not in self._edges:
                raise GraphError(f"edge references unknown node {n!r}")
        self._edges[src].append(dst)

    def nodes(self) -> List[str]:
        return list(self._nodes)

    def attrs(self, name: str) -> dict:
        return self._attrs[name]

    def srcs_of(self, name: str) -> List[str]:
        return [n for n in self._nodes if name in self._edges[n]]

    def dsts_of(self, name: str) -> List[str]:
        return list(self._edges[name])

    def topo_sort(self) -> List[str]:
        """Kahn's algorithm, stable in insertion order; raises on cycles
        (the reference asserts visited==nnodes, graph.cc:96-100)."""
        indeg = {n: 0 for n in self._nodes}
        for n, dsts in self._edges.items():
            for d in dsts:
                indeg[d] += 1
        ready = [n for n in self._nodes if indeg[n] == 0]
        order: List[str] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for d in self._edges[n]:
                indeg[d] -= 1
                if indeg[d] == 0:
                    ready.append(d)
        if len(order) != len(self._nodes):
            cyc = [n for n in self._nodes if n not in order]
            raise GraphError(f"cycle detected among {cyc}")
        return order

    def to_json(self) -> str:
        """Node-link dump in the reference's vis format (graph.cc:4-59):
        {"nodes": [{"id": ...}], "links": [{"source": i, "target": j}]}."""
        idx = {n: i for i, n in enumerate(self._nodes)}
        return json.dumps({
            "nodes": [{"id": n, **self._attrs[n]} for n in self._nodes],
            "links": [{"source": idx[s], "target": idx[d]}
                      for s in self._nodes for d in self._edges[s]],
        }, indent=2)
