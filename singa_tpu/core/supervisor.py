"""Supervised training runtime: the failure-recovery loop the reference
designed but never shipped (Worker::Resume, worker.cc:65-67 — an empty
TODO; snapshot restore commented out, blob.cc:300-320).

A `Supervisor` wraps `Trainer` in a resumable state machine:

    INIT ──▶ RESTORE ──▶ TRAIN ──▶ DONE
               ▲            │
               │  backoff   │ failure / preemption
               └────────────┘   (budgeted)

Each attempt: (re)initialize the state triple, restore the latest
*valid* checkpoint (`CheckpointManager.restore` walks back past corrupt
snapshots), fast-forward the data iterator to the restored step, and
run the trainer — which checkpoints on its cadence as usual.  A step or
pipeline failure restores and retries with exponential backoff +
seeded jitter; a simulated/real preemption restarts immediately (a
rescheduled job does not sit out a backoff).  When the retry budget is
exhausted the Supervisor raises a structured `TrainingAborted` carrying
the full failure log.

A third failure kind, `"divergence"` (utils.health.NumericDivergence —
the trainer's health monitor found non-finite or exploding numerics),
has its own budget and its own rescue policy: restore with
`skip_unhealthy=True` so the walk-back lands on the last *numerically
good* snapshot (not merely the last readable one — a snapshot taken in
a spike window carries that verdict in MANIFEST.json), optionally skip
`blame_batches` data batches at the crash step (bad-record blame), and
optionally apply a one-shot learning-rate backoff before retrying.
Like preemptions, divergences retry immediately — waiting does not fix
arithmetic.

Determinism contract (what makes recovery *testable*): the trainer's
per-step rng is fold_in(seed, step) and the data factory rebuilds the
same batch sequence, so restore-at-step-s + replay reproduces the
uninterrupted trajectory exactly — asserted in tests/test_faults.py and
scripts/fault_smoke.sh.
"""

from __future__ import annotations

import inspect
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

from .. import obs
from ..utils.faults import Backoff, Preemption, retry_call
from ..utils.health import NumericDivergence


@dataclass
class FailureRecord:
    """One supervised-run failure, as carried by TrainingAborted and
    `Supervisor.failures`."""
    attempt: int
    kind: str                 # "preemption" | "error" | "divergence"
    error: str                # repr of the exception
    last_step: int            # last step a hook observed before the crash
    restart_step: int         # step the NEXT attempt resumed from
    time: float = field(default_factory=time.time)


class TrainingAborted(RuntimeError):
    """The retry budget is spent; `failures` holds every FailureRecord
    so the operator sees the whole crash history, not just the last
    exception."""

    def __init__(self, message: str, failures: List[FailureRecord]):
        super().__init__(message)
        self.failures = list(failures)

    def __str__(self) -> str:
        lines = [super().__str__()]
        for f in self.failures:
            lines.append(f"  attempt {f.attempt}: {f.kind} after step "
                         f"{f.last_step} — {f.error}")
        return "\n".join(lines)


class Supervisor:
    """Resumable driver around a `Trainer`.

    `max_restarts` budgets *error* restarts (crash loops must stop);
    `max_preemptions` budgets preemption restarts separately and
    defaults to unlimited — preemptions are expected on preemptible
    slices and recovery from them is the point of this class.

    With no `workspace` the Supervisor still retries, but every attempt
    replays from step 0 (nothing was snapshotted) — legal for short
    runs, logged loudly for long ones.
    """

    def __init__(self, trainer, workspace: Optional[str] = None,
                 max_restarts: int = 3,
                 max_preemptions: Optional[int] = None,
                 backoff: Optional[Backoff] = None,
                 restore_retries: int = 3,
                 max_divergences: int = 2,
                 blame_batches: int = 0,
                 lr_backoff: float = 0.0,
                 log: Optional[Callable[[str], None]] = None):
        """`max_divergences`, `blame_batches`, `lr_backoff` configure
        the numeric-divergence rescue policy (docstring above; the
        trainer must carry a HealthMonitor for divergences to be
        raised at all — main.py wires both from `--health_spec`)."""
        self.trainer = trainer
        self.workspace = workspace
        self.max_restarts = max(max_restarts, 0)
        self.max_preemptions = max_preemptions
        self.backoff = backoff or Backoff(base=0.5, cap=30.0, jitter=0.25)
        self.restore_retries = max(restore_retries, 1)
        self.max_divergences = max(max_divergences, 0)
        self.blame_batches = max(blame_batches, 0)
        self.lr_backoff = lr_backoff
        self._blame: set = set()      # global batch indices to skip
        self._skip_unhealthy = False  # armed by the first divergence
        self._lr_backed_off = False   # the backoff is one-shot
        self.log = log or trainer.log
        self.failures: List[FailureRecord] = []
        cfg = trainer.cfg
        if workspace and cfg.checkpoint_frequency <= 0:
            # recovery without a cadence degrades to replay-from-zero;
            # default to ~10 snapshots over the run
            cfg.checkpoint_frequency = max(1, cfg.train_steps // 10)
            self.log(f"supervisor: checkpoint_frequency defaulted to "
                     f"{cfg.checkpoint_frequency} (workspace set, no "
                     f"cadence configured)")
        if not workspace:
            self.log("warning: supervisor has no workspace — failures "
                     "restart training from step 0 (no checkpoints)")

    # -- state machine -----------------------------------------------------
    def _fresh_state(self, seed: int):
        """INIT: the deterministic step-0 state (same seed, same init),
        sharded under the trainer's mesh exactly as main.py does —
        also the restore template."""
        params, opt = self.trainer.init(seed=seed)
        if self.trainer.mesh is not None:
            from ..parallel import shard_opt_state, shard_params
            params = shard_params(self.trainer.mesh,
                                  self.trainer.train_net, params)
            opt = shard_opt_state(self.trainer.mesh,
                                  self.trainer.train_net, opt)
        return params, opt

    def _restore(self, params, opt, seed: int,
                 corr: Optional[str] = None):
        """RESTORE: latest valid snapshot, with its own (small) retry
        budget — a flaky restore read is not a training failure.  After
        a divergence the restore also skips snapshots with a bad health
        verdict (rollback PAST the unhealthy window)."""
        if not self.workspace:
            return params, opt, 0
        with obs.span("supervisor.restore", corr=corr,
                      skip_unhealthy=self._skip_unhealthy) as sp:
            out = retry_call(
                lambda: self.trainer.resume(
                    params, opt, self.workspace,
                    skip_unhealthy=self._skip_unhealthy),
                attempts=self.restore_retries,
                backoff=Backoff(base=0.1, cap=5.0, seed=seed),
                log=self.log, what="checkpoint restore")
            sp.set(step=out[2])
        return out

    def _make_iter(self, factory: Callable[..., Iterator],
                   start_step: int) -> Iterator:
        """Fast-forward the train stream to `start_step`.  A factory
        taking a positional arg receives the step (sources that can
        seek do so cheaply); otherwise `start_step` batches are drained
        from a fresh iterator — exact replay either way, because the
        per-step path consumes exactly one batch per step.

        With blamed batches (divergence rescue), the stream is rebuilt
        from index 0, blamed indices are dropped, and the fast-forward
        drains through the FILTERED stream — so the batch offset stays
        exact across any number of later restarts."""
        if self._blame:
            it = self._drop_blamed(factory(), self._blame)
            for _ in range(start_step):
                next(it)
            return it
        if start_step > 0:
            try:
                sig = inspect.signature(factory)
                positional = [
                    p for p in sig.parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)]
            except (TypeError, ValueError):
                positional = []
            if positional:
                return factory(start_step)
        it = factory()
        for _ in range(start_step):
            next(it)
        return it

    @staticmethod
    def _drop_blamed(it: Iterator, blame) -> Iterator:
        """Yield `it` minus the batches at the blamed stream indices."""
        for i, batch in enumerate(it):
            if i in blame:
                continue
            yield batch

    def run(self, train_iter_factory: Callable[..., Iterator],
            test_iter_factory: Optional[Callable[[], Iterator]] = None,
            val_iter_factory: Optional[Callable[[], Iterator]] = None,
            seed: int = 0, scan_chunk: int = 0,
            hooks: Optional[List[Callable[[int, Dict], None]]] = None,
            resume: bool = False, feeder: Optional[bool] = None,
            feeder_depth: int = 0):
        """Run to train_steps under supervision.  Returns the trainer's
        (params, opt_state, history) — history covers the final
        (successful) attempt.  Raises TrainingAborted when the error
        budget is spent.

        `feeder`/`feeder_depth` pass through to Trainer.run's overlapped
        feed pipeline; recovery is feeder-transparent — each attempt
        rebuilds the fast-forwarded iterator and a FRESH DeviceFeeder
        whose chunk plan starts at the restored step, and failures on
        the staging thread (the `feed.stage` site) surface on the
        consumer side like any step failure."""
        errors = preemptions = divergences = 0
        attempt = 0
        last_seen = [-1]
        probes = [lambda s, m: last_seen.__setitem__(0, s)]
        if hooks:
            probes += list(hooks)
        while True:
            attempt += 1
            corr = f"attempt-{attempt}"
            monitor = getattr(self.trainer, "health", None)
            if monitor is not None:
                # rolling statistics from a poisoned attempt must not
                # leak into the retry's classification
                monitor.reset()
            params, opt = self._fresh_state(seed)
            start_step = 0
            if self.workspace and (resume or attempt > 1):
                params, opt, start_step = self._restore(params, opt,
                                                        seed, corr=corr)
                if start_step > 0:
                    self.log(f"supervisor: resumed from step "
                             f"{start_step} (attempt {attempt})")
                    obs.emit_event("supervisor.resumed",
                                   corr=corr, attempt=attempt,
                                   step=start_step)
                elif attempt > 1:
                    self.log("supervisor: no valid checkpoint; "
                             "replaying from step 0")
            it = None
            try:
                # inside the try: a data-source failure during rebuild
                # or fast-forward is retried like any step failure.
                # The attempt span carries the recovery correlation id:
                # trainer chunk / drain / checkpoint spans open inside
                # it (same thread) and inherit `attempt-N`.
                with obs.span("supervisor.attempt", corr=corr,
                              attempt=attempt, start_step=start_step):
                    it = self._make_iter(train_iter_factory, start_step)
                    return self.trainer.run(
                        params, opt, it,
                        test_iter_factory=test_iter_factory,
                        val_iter_factory=val_iter_factory,
                        start_step=start_step, seed=seed, hooks=probes,
                        workspace=self.workspace, scan_chunk=scan_chunk,
                        feeder=feeder, feeder_depth=feeder_depth)
            except Preemption as e:
                preemptions += 1
                self._record(attempt, "preemption", e, last_seen[0])
                if (self.max_preemptions is not None
                        and preemptions > self.max_preemptions):
                    raise self._abort(
                        f"{preemptions} preemptions exceed the budget "
                        f"of {self.max_preemptions}") from e
                self.log(f"supervisor: preemption at ~step "
                         f"{last_seen[0]} ({e}); restarting "
                         f"immediately")
            except NumericDivergence as e:
                divergences += 1
                self._record(attempt, "divergence", e, last_seen[0])
                if divergences > self.max_divergences:
                    raise self._abort(
                        f"{divergences} numeric divergences exceed the "
                        f"budget of {self.max_divergences}") from e
                self._rescue(e)
            except Exception as e:  # noqa: BLE001 — any runtime failure
                errors += 1
                self._record(attempt, "error", e, last_seen[0])
                if errors > self.max_restarts:
                    raise self._abort(
                        f"{errors} failures exceed the restart budget "
                        f"of {self.max_restarts}") from e
                delay = self.backoff.delay(errors - 1)
                self.log(f"supervisor: failure at ~step {last_seen[0]} "
                         f"({type(e).__name__}: {e}); retrying in "
                         f"{delay:.2f}s (error {errors}/"
                         f"{self.max_restarts} of budget)")
                time.sleep(delay)
            finally:
                close = getattr(it, "close", None) if it is not None \
                    else None
                if close is not None:
                    try:
                        close()
                    except Exception:  # pragma: no cover
                        pass

    def _rescue(self, e: NumericDivergence) -> None:
        """Divergence rescue policy: arm skip-unhealthy restores, blame
        the batches at the crash step, and (once) back off the learning
        rate.  Retries immediately — backoff sleeps don't fix NaNs."""
        with obs.span("supervisor.rescue", step=e.step):
            self._skip_unhealthy = True
            actions = ["rolling back past the unhealthy window"]
            if self.blame_batches > 0:
                first = max(e.step, 0)
                blamed = range(first, first + self.blame_batches)
                self._blame.update(blamed)
                actions.append(f"blaming batches "
                               f"[{first}, {first + self.blame_batches})")
            if self.lr_backoff and not self._lr_backed_off:
                scale = self.trainer.apply_lr_backoff(self.lr_backoff)
                self._lr_backed_off = True
                actions.append(f"LR backoff x{self.lr_backoff:g} "
                               f"(scale now {scale:g})")
            self.log(f"supervisor: numeric divergence at step {e.step} "
                     f"({e}); {'; '.join(actions)}; retrying immediately")
            obs.emit_event("supervisor.rescue", step=e.step,
                           actions=actions, error=repr(e))

    def _record(self, attempt: int, kind: str, exc: BaseException,
                last_step: int) -> None:
        restart = 0
        if self.workspace:
            try:
                from ..utils.checkpoint import CheckpointManager
                restart = CheckpointManager(
                    self.workspace, log_fn=self.log).latest_step() or 0
            except Exception:  # pragma: no cover — diagnostics only
                restart = -1
        self.failures.append(FailureRecord(
            attempt=attempt, kind=kind, error=repr(exc),
            last_step=last_step, restart_step=restart))
        obs.emit_event("supervisor.restart", corr=f"attempt-{attempt}",
                       attempt=attempt, fail_kind=kind,
                       error=repr(exc), last_step=last_step,
                       restart_step=restart)

    def _abort(self, why: str) -> TrainingAborted:
        obs.emit_event("supervisor.abort", why=why,
                       failures=len(self.failures))
        return TrainingAborted(f"training aborted: {why}", self.failures)
