"""Zero-copy binary transport for the serving hot path: persistent
framed connections, shared-memory token rings, and batched token
flushes.

Every hop used to cross stdlib HTTP with one JSON chunk per decoded
token and one fresh TCP connection per request — fine at dozens of
requests, a wall at fleet scale.  "RPC Considered Harmful" (arxiv
1805.08430) is the playbook applied here: persistent connections,
explicit length-prefixed framing, and no boxed per-message
serialization on the per-token path.  HTTP/JSON stays as the
always-on debug surface; the binary transport is a negotiated upgrade
(`NegotiatingEngineHandle`) that degrades back to HTTP on any
transport-level failure — counted, never a lost request.

Frame layout (all little-endian)::

    +----+----+-----+------+------+--------+------------+-------------+
    |magic|ver|kind |flags | rsv  | req_id | header_len | payload_len |
    | 2B  |1B | 1B  | 1B   | 1B   |  u32   |    u16     |    u32      |
    +----+----+-----+------+------+--------+------------+-------------+
    | QoS header (REQ only): deadline_ms i64, priority u8,            |
    |   resume_from u32, parent_span u64, then tenant / trace id /    |
    |   session id as u16-length-prefixed strings                     |
    +------------------------------------------------------------------+
    | payload (kind-specific flat struct or JSON, below)               |
    +------------------------------------------------------------------+

The QoS header is the complete wire envelope the HTTP headers grew
over PRs 12-19 — deadline (X-Deadline-Ms), priority (X-Priority),
tenant (X-Tenant), trace/parent ids (X-Trace-Id / X-Parent-Span),
session id (X-Session-Id, reserved at the engine tier) and
resume_from — designed once, mapped both ways by serve/qos.py so the
two wire surfaces can never drift.

Frame kinds:

    HELLO   connection handshake, both directions (empty payload; the
            preamble's version byte is the negotiation)
    REQ     one request: op u8 (generate|predict|stream|probe|stats|
            reload), timeout_ms i64, max_new i32, step i32, n_tokens
            u32, then the prompt as raw int32s
    RESULT  unary reply: JSON body (predict logprobs etc. — once per
            request, not per token)
    TOKENS  one flushed batch of decoded tokens: first_i u32, count
            u32, then raw int32 token ids — NO per-token objects; the
            sender gather-writes the token ring's memoryview straight
            into the socket
    DONE    stream terminal: JSON summary line (once per stream)
    ERR     mapped failure: code u8, retry_after_ms u32, utf-8
            message (the status-code vocabulary of the HTTP surface)
    CANCEL  client abandons req_id (hedge loser, closed generator)

Malformed input (bad magic, version skew, oversized length prefix,
truncated frame) is an honest counted error (`wire_malformed_total`)
and a closed connection — never a hang, never a crash, never a
partially-trusted payload.

Decode tokens are flushed in batched frames under the
`flush_tokens`/`flush_ms` knobs (ServeSpec for engine servers,
RouterSpec for the fleet frontend): a flush goes out when
`flush_tokens` tokens are buffered or `flush_ms` has passed since the
batch opened — and the FIRST token of a stream always flushes
immediately, so first-token latency (a gated stage) never pays for
batching.  The same knobs batch the HTTP ndjson paths (one chunk
carrying several lines), so both surfaces share one flush story.

`singa_wire_*` metrics split serialization time out of the stage
taxonomy: `ser/deser_seconds_total` for the binary codec,
`json_ser/json_deser_seconds_total` for the JSON surface — the
A/B proof of where `bench.py --transport-smoke`'s saved time comes
from.  Fault site `wire.frame` (utils/faults.py) drops, corrupts, or
tears one outbound frame; all three degrade to a counted reconnect
or a per-request failure the Router's retry/failover machinery
absorbs.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
from itertools import count as _it_count
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..utils import faults
from . import qos
from .batcher import Cancelled, DeadlineExpired, Overloaded

MAGIC = b"SW"
VERSION = 1

#: frame kinds
K_HELLO, K_REQ, K_RESULT, K_TOKENS, K_DONE, K_ERR, K_CANCEL = \
    range(1, 8)
KIND_NAMES = {K_HELLO: "hello", K_REQ: "req", K_RESULT: "result",
              K_TOKENS: "tokens", K_DONE: "done", K_ERR: "err",
              K_CANCEL: "cancel"}

#: request ops
OP_GENERATE, OP_PREDICT, OP_STREAM, OP_PROBE, OP_STATS, OP_RELOAD = \
    range(1, 7)
_OP_NAMES = {OP_GENERATE: "generate", OP_PREDICT: "predict",
             OP_STREAM: "stream", OP_PROBE: "probe",
             OP_STATS: "stats", OP_RELOAD: "reload"}

#: error codes — the frame twin of the HTTP status mapping
E_UNAVAILABLE, E_OVERLOADED, E_DEADLINE, E_BADREQ, E_CANCELLED, \
    E_INTERNAL = range(1, 7)

#: hostile-input bounds: a garbage length prefix must never allocate
MAX_HEADER_LEN = 1 << 12
MAX_PAYLOAD_LEN = 1 << 26

_PREAMBLE = struct.Struct("<2sBBBBIHI")     # magic ver kind flags rsv
                                            # req_id hlen plen
_QOS_HDR = struct.Struct("<qBIQ")           # deadline_ms prio
                                            # resume_from parent_span
_REQ_HDR = struct.Struct("<BqiiI")          # op timeout_ms max_new
                                            # step n_tokens
_TOK_HDR = struct.Struct("<II")             # first_i count
_ERR_HDR = struct.Struct("<BI")             # code retry_after_ms
_STR_LEN = struct.Struct("<H")

_I32_NONE = -(1 << 31)                      # "no step" sentinel


class WireError(RuntimeError):
    """A malformed frame: bad magic, version skew, oversized length
    prefix, or a truncation mid-frame.  The connection that produced
    it is closed — a peer that frames wrong once cannot be trusted to
    frame right next time."""


class WireUnavailable(RuntimeError):
    """A TRANSPORT-level failure on the binary path (connect refused,
    handshake failed, connection died before the reply) — distinct
    from an engine-reported error so `NegotiatingEngineHandle` knows
    when falling back to HTTP can actually help."""


# -- metrics -----------------------------------------------------------------

class WireStats:
    """Binary-transport counters, exported as `singa_wire_*_total`
    (the WalStats mold) plus the serialization-time split the
    transport A/B gates on."""

    FIELDS = ("frames_tx", "frames_rx", "bytes_tx", "bytes_rx",
              "tokens_tx", "token_flushes", "malformed", "reconnects",
              "fallbacks", "faulted_frames", "conns_opened",
              "conns_closed", "cancels_tx")
    #: nanosecond accumulators exported as *_seconds_total
    NS_FIELDS = ("ser_ns", "deser_ns", "json_ser_ns", "json_deser_ns")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS + self.NS_FIELDS:
            setattr(self, f, 0)

    def count(self, fieldname: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, fieldname, getattr(self, fieldname) + n)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
            for f in self.NS_FIELDS:
                out[f.replace("_ns", "_seconds")] = \
                    getattr(self, f) / 1e9
            return out

    def register_into(self, registry,
                      prefix: str = "singa_wire") -> None:
        from ..obs.metrics import Sample

        def collect():
            snap = self.snapshot()
            out = [Sample(f"{prefix}_{k}_total", "counter",
                          f"binary transport counter {k!r}",
                          float(snap[k])) for k in self.FIELDS]
            for f in self.NS_FIELDS:
                k = f.replace("_ns", "_seconds")
                out.append(Sample(
                    f"{prefix}_{k}_total", "counter",
                    f"cumulative {k.replace('_', ' ')} on the "
                    f"serving wire", float(snap[k])))
            return out

        registry.register_collector(collect)


#: process-wide default — every transport endpoint in this process
#: shares one serialization/malformed story, exactly like obs.perf
STATS = WireStats()


def timed_json_dumps(obj, stats: Optional[WireStats] = None) -> bytes:
    """json.dumps with the time charged to the wire's JSON
    serialization split (the HTTP ndjson hot path)."""
    t0 = time.perf_counter_ns()
    data = json.dumps(obj).encode()
    (stats or STATS).count("json_ser_ns",
                           time.perf_counter_ns() - t0)
    return data


def timed_json_loads(data, stats: Optional[WireStats] = None):
    t0 = time.perf_counter_ns()
    out = json.loads(data)
    (stats or STATS).count("json_deser_ns",
                           time.perf_counter_ns() - t0)
    return out


# -- QoS header <-> frame ----------------------------------------------------

def _pack_str(s: Optional[str]) -> bytes:
    b = ("" if s is None else str(s)).encode()[:1024]
    return _STR_LEN.pack(len(b)) + b


def encode_qos_header(deadline: Optional[float] = None,
                      priority: Optional[str] = None,
                      tenant: Optional[str] = None,
                      trace=None, sid: Optional[str] = None,
                      resume_from: int = 0) -> bytes:
    """The complete QoS envelope as one flat header (module
    docstring).  `trace` is the `(trace_id, span_id)` pair the HTTP
    surface carries as X-Trace-Id / X-Parent-Span."""
    trace_id, parent = (trace if trace else (None, 0))
    fixed = _QOS_HDR.pack(
        qos.deadline_to_ms(deadline),
        qos.priority_to_code(priority),
        int(resume_from) & 0xFFFFFFFF,
        int(parent or 0) & 0xFFFFFFFFFFFFFFFF)
    return b"".join((fixed, _pack_str(tenant), _pack_str(trace_id),
                     _pack_str(sid)))


def decode_qos_header(buf: bytes) -> Dict[str, Any]:
    """Inverse of encode_qos_header, re-anchoring the deadline onto
    THIS process's clock (qos.deadline_from_ms).  Raises WireError on
    truncation or a skewed priority code."""
    try:
        dl_ms, prio, resume_from, parent = _QOS_HDR.unpack_from(buf, 0)
        off = _QOS_HDR.size
        strs = []
        for _ in range(3):
            (n,) = _STR_LEN.unpack_from(buf, off)
            off += _STR_LEN.size
            if off + n > len(buf):
                raise ValueError("truncated string field")
            strs.append(buf[off:off + n].decode() if n else None)
            off += n
        tenant, trace_id, sid = strs
        return {"deadline": qos.deadline_from_ms(dl_ms),
                "priority": qos.priority_from_code(prio),
                "tenant": qos.check_tenant(tenant),
                "trace": ((trace_id, int(parent)) if trace_id
                          else None),
                "sid": sid,
                "resume_from": int(resume_from)}
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        raise WireError(f"malformed QoS header: {e}") from e


# -- payload codecs ----------------------------------------------------------

def encode_request(op: int, tokens=None,
                   timeout: Optional[float] = None,
                   max_new: Optional[int] = None,
                   step: Optional[int] = None) -> bytes:
    if tokens is None:
        arr = np.empty(0, np.int32)
    else:
        arr = np.ascontiguousarray(tokens, dtype=np.int32)
    fixed = _REQ_HDR.pack(
        op,
        -1 if timeout is None else max(int(timeout * 1000), 0),
        -1 if max_new is None else int(max_new),
        _I32_NONE if step is None else int(step),
        arr.size)
    return fixed + arr.tobytes()


def decode_request(buf: bytes) -> Dict[str, Any]:
    try:
        op, t_ms, max_new, step, n = _REQ_HDR.unpack_from(buf, 0)
        if op not in _OP_NAMES:
            raise ValueError(f"unknown op {op}")
        need = _REQ_HDR.size + 4 * n
        if len(buf) < need:
            raise ValueError(f"token array truncated: want {need} "
                             f"bytes, have {len(buf)}")
        toks = np.frombuffer(buf, np.int32, count=n,
                             offset=_REQ_HDR.size)
        return {"op": op, "mode": _OP_NAMES[op],
                "timeout": None if t_ms < 0 else t_ms / 1000.0,
                "max_new": None if max_new < 0 else int(max_new),
                "step": None if step == _I32_NONE else int(step),
                "tokens": toks}
    except (struct.error, ValueError) as e:
        raise WireError(f"malformed request payload: {e}") from e


def token_frame_parts(first_i: int, view) -> List[Any]:
    """TOKENS payload as gather-write parts: the flat header plus the
    int32 token view itself — the ring's memory goes straight to the
    socket, zero intermediate copies."""
    arr = np.ascontiguousarray(view, dtype=np.int32)
    return [_TOK_HDR.pack(int(first_i) & 0xFFFFFFFF, arr.size),
            memoryview(arr).cast("B")]


def decode_tokens(buf: bytes) -> Tuple[int, np.ndarray]:
    try:
        first_i, n = _TOK_HDR.unpack_from(buf, 0)
        need = _TOK_HDR.size + 4 * n
        if len(buf) < need:
            raise ValueError(f"token batch truncated: want {need} "
                             f"bytes, have {len(buf)}")
        return int(first_i), np.frombuffer(buf, np.int32, count=n,
                                           offset=_TOK_HDR.size)
    except (struct.error, ValueError) as e:
        raise WireError(f"malformed token batch: {e}") from e


def encode_error(code: int, message: str,
                 retry_after: float = 0.0) -> bytes:
    return _ERR_HDR.pack(code,
                         max(int(retry_after * 1000), 0) & 0xFFFFFFFF
                         ) + str(message).encode()[:4096]


def decode_error(buf: bytes) -> Tuple[int, float, str]:
    try:
        code, ra_ms = _ERR_HDR.unpack_from(buf, 0)
        msg = buf[_ERR_HDR.size:].decode(errors="replace")
        return int(code), ra_ms / 1000.0, msg
    except struct.error as e:
        raise WireError(f"malformed error payload: {e}") from e


def error_for_exception(e: BaseException) -> Tuple[int, float, str]:
    """Server-side mapping: exception -> (code, retry_after, msg) —
    the frame twin of the HTTP handler's status mapping."""
    if isinstance(e, Overloaded):
        return E_OVERLOADED, float(getattr(e, "retry_after", 0.0)), \
            str(e)
    if isinstance(e, (DeadlineExpired, TimeoutError)):
        return E_DEADLINE, 0.0, str(e)
    if isinstance(e, Cancelled):
        return E_CANCELLED, 0.0, str(e)
    if isinstance(e, (ValueError, KeyError)):
        return E_BADREQ, 0.0, str(e)
    return E_INTERNAL, 0.0, f"{type(e).__name__}: {e}"


def exception_for_error(code: int, retry_after: float, msg: str,
                        engine: str) -> BaseException:
    """Client-side inverse: the Router's exception vocabulary."""
    from .router import EngineUnavailable
    if code == E_OVERLOADED:
        return Overloaded(msg, retry_after=retry_after)
    if code == E_DEADLINE:
        return DeadlineExpired(msg)
    if code == E_BADREQ:
        return ValueError(msg)
    if code == E_CANCELLED:
        return Cancelled(msg)
    return EngineUnavailable(f"engine {engine}: {msg}")


# -- frame send / receive ----------------------------------------------------

def frame_parts(kind: int, req_id: int, header: bytes = b"",
                payload_parts=()) -> List[Any]:
    plen = sum(len(p) for p in payload_parts)
    if len(header) > MAX_HEADER_LEN or plen > MAX_PAYLOAD_LEN:
        raise WireError(f"frame too large: header {len(header)}, "
                        f"payload {plen}")
    parts = [_PREAMBLE.pack(MAGIC, VERSION, kind, 0, 0,
                            int(req_id) & 0xFFFFFFFF,
                            len(header), plen)]
    if header:
        parts.append(header)
    parts.extend(payload_parts)
    return parts


def send_frame(sock, wlock: threading.Lock, kind: int, req_id: int,
               header: bytes = b"", payload_parts=(),
               stats: Optional[WireStats] = None) -> None:
    """Encode + gather-write one frame (socket.sendmsg: the token
    ring's memoryview reaches the kernel without an intermediate
    join).  Consults the `wire.frame` fault site: "error" drops the
    frame and fails the connection, "corrupt" flips the magic so the
    receiver counts it malformed, "torn" writes half the frame then
    fails the sender.  Raises ConnectionError/OSError on any send
    failure — the caller owns closing the connection."""
    st = stats or STATS
    t0 = time.perf_counter_ns()
    parts = frame_parts(kind, req_id, header, payload_parts)
    nbytes = sum(len(p) for p in parts)
    torn = False
    try:
        kind_f = faults.maybe_fault("wire.frame")
        if kind_f == "torn":
            torn = True
    except faults.CorruptRecord:
        st.count("faulted_frames")
        parts[0] = b"XX" + bytes(parts[0][2:])
    except faults.FaultError as e:
        st.count("faulted_frames")
        raise ConnectionError(f"injected wire.frame drop: {e}") from e
    st.count("ser_ns", time.perf_counter_ns() - t0)
    with wlock:
        if torn:
            st.count("faulted_frames")
            buf = b"".join(bytes(p) for p in parts)
            sock.sendall(buf[:max(len(buf) // 2, 1)])
            raise ConnectionError("injected wire.frame tear")
        try:
            sock.sendmsg(parts)
        except (AttributeError, NotImplementedError):
            sock.sendall(b"".join(bytes(p) for p in parts))
    st.count("frames_tx")
    st.count("bytes_tx", nbytes)


class FrameReader:
    """Buffered frame decoder over one socket.  `read_frame()` returns
    (kind, flags, req_id, header, payload), None on a clean EOF at a
    frame boundary, and raises WireError — counted
    `wire_malformed_total` — on anything else."""

    def __init__(self, sock, stats: Optional[WireStats] = None):
        self._f = sock.makefile("rb")
        self.stats = stats or STATS

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    def _malformed(self, why: str) -> WireError:
        self.stats.count("malformed")
        return WireError(why)

    def read_frame(self):
        pre = self._f.read(_PREAMBLE.size)
        if not pre:
            return None                      # clean EOF
        if len(pre) < _PREAMBLE.size:
            raise self._malformed(
                f"truncated preamble ({len(pre)} bytes)")
        t0 = time.perf_counter_ns()
        magic, ver, kind, flags, _rsv, req_id, hlen, plen = \
            _PREAMBLE.unpack(pre)
        if magic != MAGIC:
            raise self._malformed(f"bad magic {magic!r}")
        if ver != VERSION:
            raise self._malformed(
                f"version skew: peer speaks v{ver}, this process "
                f"v{VERSION}")
        if kind not in KIND_NAMES:
            raise self._malformed(f"unknown frame kind {kind}")
        if hlen > MAX_HEADER_LEN or plen > MAX_PAYLOAD_LEN:
            raise self._malformed(
                f"oversized length prefix (header {hlen}, payload "
                f"{plen})")
        header = self._f.read(hlen) if hlen else b""
        payload = self._f.read(plen) if plen else b""
        if len(header) < hlen or len(payload) < plen:
            raise self._malformed("frame truncated mid-body")
        self.stats.count("frames_rx")
        self.stats.count("bytes_rx", _PREAMBLE.size + hlen + plen)
        self.stats.count("deser_ns", time.perf_counter_ns() - t0)
        return kind, flags, req_id, header, payload


# -- token ring --------------------------------------------------------------

class TokenRing:
    """Bounded shared-memory token channel for the in-process hop: a
    preallocated int32 buffer with absolute head/tail cursors under
    one Condition.  The producer appends raw token ids (no per-token
    object), the consumer peeks CONTIGUOUS batches as zero-copy numpy
    views — one lock round-trip per batch — and `consume()`s them
    once delivered, which is what keeps the view safe: space is only
    reusable after the consumer is done with it.  `finish`/`fail`
    carry the stream terminal through the same channel."""

    def __init__(self, capacity: int = 512):
        if int(capacity) < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._buf = np.empty(int(capacity), np.int32)
        self._cap = int(capacity)
        self._head = 0                       # absolute: next unread
        self._tail = 0                       # absolute: next write
        self._cv = threading.Condition()
        self._result: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return self._tail - self._head

    def push_many(self, tokens, timeout: Optional[float] = None
                  ) -> None:
        """Append token ids, blocking while the ring is full (the
        consumer owes a consume()).  Raises RuntimeError on a closed
        ring and TimeoutError when the consumer never drains."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        off = 0
        with self._cv:
            while off < toks.size:
                if self._closed:
                    raise RuntimeError("push to a closed TokenRing")
                free = self._cap - (self._tail - self._head)
                if free == 0:
                    if not self._cv.wait(timeout):
                        raise TimeoutError(
                            "TokenRing full: consumer stalled")
                    continue
                n = min(free, toks.size - off)
                pos = self._tail % self._cap
                run = min(n, self._cap - pos)
                self._buf[pos:pos + run] = toks[off:off + run]
                if n > run:
                    self._buf[0:n - run] = toks[off + run:off + n]
                self._tail += n
                off += n
                self._cv.notify_all()

    def finish(self, result: Dict[str, Any]) -> None:
        with self._cv:
            self._result = result
            self._closed = True
            self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cv:
            self._error = exc
            self._closed = True
            self._cv.notify_all()

    def peek_batch(self, max_n: int = 64,
                   timeout: Optional[float] = None):
        """Next contiguous unread run as ("toks", first_abs_index,
        int32 view) — zero-copy; call `consume(len(view))` when
        delivered.  ("done", result) after the producer finished and
        everything is drained.  Raises the producer's failure, or
        TimeoutError when nothing arrives in time."""
        with self._cv:
            while self._tail == self._head:
                if self._closed:
                    if self._error is not None:
                        raise self._error
                    return ("done", self._result)
                if not self._cv.wait(timeout):
                    raise TimeoutError("TokenRing stalled")
            n = min(int(max_n), self._tail - self._head)
            pos = self._head % self._cap
            n = min(n, self._cap - pos)      # contiguous run only
            return ("toks", self._head, self._buf[pos:pos + n])

    def consume(self, n: int) -> None:
        with self._cv:
            self._head = min(self._head + int(n), self._tail)
            self._cv.notify_all()


# -- ndjson flush batching ---------------------------------------------------

class LineCoalescer:
    """Batch serialized ndjson lines into one chunked write under the
    flush_tokens/flush_ms knobs.  The FIRST line of a stream (and any
    urgent line: terminals, errors) flushes immediately — batching
    must never tax first-token latency, which is a gated stage."""

    def __init__(self, write_fn, flush_tokens: int = 8,
                 flush_ms: float = 4.0,
                 stats: Optional[WireStats] = None):
        self._write = write_fn
        self.flush_tokens = max(int(flush_tokens), 1)
        self.flush_s = max(float(flush_ms), 0.0) / 1000.0
        self._buf: List[bytes] = []
        self._opened = 0.0
        self._first = True
        self._stats = stats or STATS

    def add(self, line: bytes, urgent: bool = False) -> None:
        if not self._buf:
            self._opened = time.monotonic()
        self._buf.append(line)
        if urgent or self._first or \
                len(self._buf) >= self.flush_tokens or \
                time.monotonic() - self._opened >= self.flush_s:
            self._first = False
            self.flush()

    def flush(self) -> None:
        if self._buf:
            data = b"".join(self._buf)
            self._buf = []
            self._stats.count("token_flushes")
            self._write(data)


# -- binary transport server -------------------------------------------------

class BinaryTransportServer:
    """The framed listener beside an `InferenceServer`'s HTTP
    frontend: long-lived connections, multiplexed in-flight requests
    (one worker thread per REQ, demuxed by req_id), batched TOKENS
    flushes straight off a TokenRing.  A malformed frame closes the
    connection (counted); everything else on that socket keeps its
    own req_id lane."""

    def __init__(self, server, host: str = "127.0.0.1",
                 port: int = 0,
                 flush_tokens: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 stats: Optional[WireStats] = None, log_fn=print):
        self.server = server
        self.stats = stats or STATS
        self.log = log_fn
        spec = server.engine.spec
        self.flush_tokens = int(flush_tokens
                                if flush_tokens is not None
                                else getattr(spec, "flush_tokens", 8))
        self.flush_ms = float(flush_ms if flush_ms is not None
                              else getattr(spec, "flush_ms", 4.0))
        self._host, self._port = host, int(port)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()

    @property
    def address(self):
        return self._sock.getsockname() if self._sock else None

    def start(self) -> "BinaryTransportServer":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(64)
        self._sock = s
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="wire-accept", daemon=True)
        self._accept_thread.start()
        self.log(f"serve: wire on {self.address[0]}:"
                 f"{self.address[1]}")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            # shutdown() the LISTENING socket first: close() alone
            # does not unblock a thread parked in accept() (the
            # in-flight syscall pins the file description, so the
            # port would keep accepting), shutdown() does
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(5.0)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # shutdown() unblocks the conn_loop thread parked in recv;
            # it then closes its own reader and drops the conn
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._accept_thread = None

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock = self._sock
                if sock is None:
                    return
                conn, _addr = sock.accept()
            except OSError:
                return                       # listener closed
            if self._stop.is_set():          # raced stop(): refuse
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            self.stats.count("conns_opened")
            threading.Thread(target=self._conn_loop, args=(conn,),
                             name="wire-conn", daemon=True).start()

    def _drop_conn(self, conn) -> None:
        with self._lock:
            self._conns.discard(conn)
        try:
            conn.close()
        except OSError:
            pass
        self.stats.count("conns_closed")

    def _conn_loop(self, conn) -> None:
        """One connection's demux loop: HELLO handshake, then every
        REQ gets its own worker thread writing replies through the
        shared write lock.  Any malformed frame — or any transport
        error — ends the WHOLE connection; in-flight workers notice
        on their next write and give up."""
        reader = FrameReader(conn, stats=self.stats)
        wlock = threading.Lock()
        cancels: Dict[int, threading.Event] = {}
        try:
            first = reader.read_frame()
            if first is None:
                return
            if first[0] != K_HELLO:
                raise reader._malformed(
                    f"expected HELLO, got {KIND_NAMES.get(first[0])}")
            send_frame(conn, wlock, K_HELLO, 0, stats=self.stats)
            while True:
                frame = reader.read_frame()
                if frame is None:
                    return
                kind, _flags, req_id, header, payload = frame
                if kind == K_CANCEL:
                    ev = cancels.get(req_id)
                    if ev is not None:
                        ev.set()
                    continue
                if kind != K_REQ:
                    continue                 # ignorable (future kinds
                                             # share the version)
                cancel = threading.Event()
                cancels[req_id] = cancel
                threading.Thread(
                    target=self._serve_req,
                    args=(conn, wlock, req_id, header, payload,
                          cancel, cancels),
                    name=f"wire-req-{req_id}", daemon=True).start()
        except WireError as e:
            obs.emit_event("wire.malformed", why=str(e))
            self.log(f"warning: wire connection closed on malformed "
                     f"frame: {e}")
        except (ConnectionError, OSError):
            pass                             # peer went away
        finally:
            for ev in cancels.values():
                ev.set()                     # orphaned workers stop
            reader.close()
            self._drop_conn(conn)

    def _send_err(self, conn, wlock, req_id,
                  e: BaseException) -> None:
        code, retry_after, msg = error_for_exception(e)
        try:
            send_frame(conn, wlock, K_ERR, req_id,
                       payload_parts=[encode_error(code, msg,
                                                   retry_after)],
                       stats=self.stats)
        except (ConnectionError, OSError):
            pass                             # conn already dead

    def _serve_req(self, conn, wlock, req_id, header, payload,
                   cancel, cancels) -> None:
        srv = self.server
        try:
            try:
                q = decode_qos_header(header) if header else {
                    "deadline": None, "priority": None,
                    "tenant": "default", "trace": None, "sid": None,
                    "resume_from": 0}
                req = decode_request(payload)
            except WireError as e:
                # the frame ITSELF parsed (length/magic fine) but the
                # body is skewed: an honest per-request error, the
                # connection survives
                self._send_err(conn, wlock, req_id, ValueError(str(e)))
                return
            tr = q["trace"][0] if q["trace"] else None
            psid = q["trace"][1] if q["trace"] else None
            op = req["op"]
            priority = qos.check_priority(q["priority"])
            if op == OP_PROBE:
                h = dict(srv.engine.health())
                h["queue_depth"] = srv.engine.stats.queue_depth
                self._reply_json(conn, wlock, req_id, h)
                return
            if op == OP_STATS:
                self._reply_json(conn, wlock, req_id, srv.snapshot())
                return
            if op == OP_RELOAD:
                with obs.span("serve.reload", trace=tr, parent=psid,
                              step=req["step"]):
                    outcome = srv.engine.reload_to(req["step"])
                self._reply_json(conn, wlock, req_id,
                                 {"outcome": outcome,
                                  "step": srv.engine.params_step})
                return
            with obs.span("serve.request", trace=tr, parent=psid,
                          mode=req["mode"], priority=priority,
                          tenant=q["tenant"], transport="wire"):
                if op == OP_STREAM:
                    self._serve_stream(conn, wlock, req_id, q, req,
                                       priority, cancel)
                    return
                call = (srv.generate if op == OP_GENERATE
                        else srv.predict)
                out = call(req["tokens"], timeout=req["timeout"],
                           deadline=q["deadline"], priority=priority,
                           tenant=q["tenant"], cancel_event=cancel,
                           **({"max_new": req["max_new"]}
                              if op == OP_GENERATE else {}))
            self._reply_json(conn, wlock, req_id, out)
        except (ConnectionError, OSError):
            pass                             # conn died under us
        except BaseException as e:  # noqa: BLE001 — mapped reply
            self._send_err(conn, wlock, req_id, e)
        finally:
            cancels.pop(req_id, None)

    def _reply_json(self, conn, wlock, req_id, obj,
                    kind: int = K_RESULT) -> None:
        send_frame(conn, wlock, kind, req_id,
                   payload_parts=[timed_json_dumps(obj,
                                                   self.stats)],
                   stats=self.stats)

    def _serve_stream(self, conn, wlock, req_id, q, req, priority,
                      cancel) -> None:
        """Admission, then batched TOKENS flushes off a TokenRing:
        the ring's int32 views gather-write straight into the socket
        (`token_frame_parts`).  The first token flushes alone; later
        batches linger up to flush_ms for up to flush_tokens."""
        srv = self.server
        t0 = time.monotonic()
        ticket = srv.generate_stream(
            req["tokens"], timeout=req["timeout"],
            max_new=req["max_new"], deadline=q["deadline"],
            priority=priority, tenant=q["tenant"],
            cancel_event=cancel, resume_from=q["resume_from"])
        budget = srv._wait_budget(req["timeout"], q["deadline"])
        ring = TokenRing(max(self.flush_tokens * 8, 64))
        i = ticket.first_index
        first = True
        linger = self.flush_ms / 1000.0
        while True:
            evs = ticket.drain_events(
                max_n=1 if first else self.flush_tokens,
                timeout=budget, linger_s=0.0 if first else linger)
            first = False
            toks = [p for k, p in evs if k == "tok"]
            tail = evs[-1] if evs[-1][0] != "tok" else None
            if toks:
                ring.push_many(toks)
                left = len(toks)
                while left > 0:
                    _kind, start, view = ring.peek_batch(left)
                    send_frame(
                        conn, wlock, K_TOKENS, req_id,
                        payload_parts=token_frame_parts(
                            i, view),
                        stats=self.stats)
                    n = len(view)
                    ring.consume(n)
                    i += n
                    left -= n
                self.stats.count("tokens_tx", len(toks))
                self.stats.count("token_flushes")
            if tail is None:
                continue
            if tail[0] == "failed":
                raise tail[1]
            out = dict(tail[1])
            out["done"] = True
            out["latency_ms"] = round((time.monotonic() - t0) * 1e3,
                                      3)
            self._reply_json(conn, wlock, req_id, out, kind=K_DONE)
            return


# -- binary client -----------------------------------------------------------

class _BinConn:
    """One persistent framed connection: socket + demux reader thread.
    Frames are routed to per-request queues by req_id; a transport
    death fails every in-flight lane with the SAME exception so each
    caller can map it for its own phase (admission vs mid-stream)."""

    def __init__(self, address, connect_timeout_s: float,
                 stats: WireStats):
        self.stats = stats
        self.sock = socket.create_connection(
            address, timeout=connect_timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                             1)
        self.sock.settimeout(None)
        self.wlock = threading.Lock()
        self._reader = FrameReader(self.sock, stats=stats)
        self._lanes: Dict[int, "queue.Queue"] = {}
        self._lanes_lock = threading.Lock()
        self._ids = _it_count(1)
        self.alive = True
        stats.count("conns_opened")
        # handshake synchronously, under the connect timeout: a peer
        # that is not a wire server must fail HERE, not on first use
        self.sock.settimeout(connect_timeout_s)
        try:
            send_frame(self.sock, self.wlock, K_HELLO, 0, stats=stats)
            got = self._reader.read_frame()
            if got is None or got[0] != K_HELLO:
                raise WireUnavailable(
                    "handshake failed: no HELLO from peer")
        except WireError as e:
            self._reader.close()
            self.sock.close()
            raise WireUnavailable(f"handshake failed: {e}") from e
        except Exception:
            self._reader.close()
            self.sock.close()
            raise
        self.sock.settimeout(None)
        self._thread = threading.Thread(target=self._demux,
                                        name="wire-demux",
                                        daemon=True)
        self._thread.start()

    def open_lane(self) -> Tuple[int, "queue.Queue"]:
        req_id = next(self._ids) & 0xFFFFFFFF
        q: "queue.Queue" = queue.Queue()
        with self._lanes_lock:
            if not self.alive:
                raise WireUnavailable("connection already dead")
            self._lanes[req_id] = q
        return req_id, q

    def close_lane(self, req_id: int) -> None:
        with self._lanes_lock:
            self._lanes.pop(req_id, None)

    def send(self, kind: int, req_id: int, header: bytes = b"",
             payload_parts=()) -> None:
        try:
            send_frame(self.sock, self.wlock, kind, req_id, header,
                       payload_parts, stats=self.stats)
        except (ConnectionError, OSError) as e:
            self.close(e)
            raise

    def _demux(self) -> None:
        err: BaseException = WireUnavailable("connection closed")
        try:
            while True:
                frame = self._reader.read_frame()
                if frame is None:
                    break
                kind, _flags, req_id, header, payload = frame
                with self._lanes_lock:
                    lane = self._lanes.get(req_id)
                if lane is not None:
                    lane.put(("frame", kind, header, payload))
        except WireError as e:
            err = WireUnavailable(f"malformed reply frame: {e}")
        except (ConnectionError, OSError) as e:
            err = WireUnavailable(f"connection lost: {e}")
        finally:
            self.close(err)
            # the demux thread OWNS the buffered reader: closing it
            # from any other thread would block on the buffer lock we
            # hold while parked in recv
            self._reader.close()

    def close(self, err: Optional[BaseException] = None) -> None:
        with self._lanes_lock:
            if not self.alive:
                return
            self.alive = False
            lanes = list(self._lanes.values())
            self._lanes.clear()
        e = err if err is not None else \
            WireUnavailable("connection closed")
        for lane in lanes:
            lane.put(("conn_err", e))
        # shutdown() first: it unblocks a demux thread parked in recv
        # (close() alone would not, and the fd lingers behind the
        # reader's io-ref anyway)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.stats.count("conns_closed")


class BinaryEngineHandle:
    """Worker behind a framed socket: the binary twin of
    `HttpEngineHandle`, same duck-typed surface (`probe`,
    `stats_snapshot`, `request`, `request_stream`, `reload`) and the
    same exception vocabulary, so Router dispatch, hedge legs,
    failover resumes, and WAL'd session replay ride it unchanged.
    ONE long-lived connection multiplexes every in-flight request;
    a dead connection is rebuilt on the next call (counted
    `wire_reconnects_total`)."""

    def __init__(self, name: str, address,
                 connect_timeout_s: float = 5.0,
                 stats: Optional[WireStats] = None):
        self.name = name
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self.address = (address[0], int(address[1]))
        self.connect_timeout_s = float(connect_timeout_s)
        self.stats = stats or STATS
        self._conn: Optional[_BinConn] = None
        self._conn_lock = threading.Lock()

    # -- connection management ----------------------------------------------
    def _connect(self) -> Tuple[_BinConn, bool]:
        """(connection, was_reused).  Raises WireUnavailable when the
        peer is unreachable or does not speak the protocol."""
        with self._conn_lock:
            if self._conn is not None and self._conn.alive:
                return self._conn, True
            if self._conn is not None:
                self.stats.count("reconnects")
            try:
                self._conn = _BinConn(self.address,
                                      self.connect_timeout_s,
                                      self.stats)
            except (ConnectionError, OSError, TimeoutError) as e:
                self._conn = None
                raise WireUnavailable(
                    f"engine {self.name} unreachable at "
                    f"{self.address[0]}:{self.address[1]}: {e}"
                ) from e
            return self._conn, False

    def close(self) -> None:
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    def _open(self, op: int, header: bytes, tokens=None,
              timeout=None, max_new=None, step=None):
        """Send one REQ, retrying ONCE on a stale reused connection
        (the keep-alive race: the peer closed an idle socket between
        our calls — nothing was processed, resending is safe)."""
        for attempt in (0, 1):
            conn, reused = self._connect()
            req_id, lane = conn.open_lane()
            try:
                conn.send(K_REQ, req_id, header,
                          [encode_request(op, tokens, timeout,
                                          max_new, step)])
                return conn, req_id, lane
            except (ConnectionError, OSError) as e:
                conn.close_lane(req_id)
                if not reused or attempt == 1:
                    raise WireUnavailable(
                        f"engine {self.name} send failed: {e}"
                    ) from e
        raise WireUnavailable(f"engine {self.name} send failed")

    def _wait(self, conn, req_id: int, lane, budget: float):
        """One reply frame for req_id, or the mapped failure.  A
        transport death or a silence past `budget` is
        WireUnavailable — the engine may be fine, the WIRE is not."""
        try:
            got = lane.get(timeout=max(budget, 0.1))
        except queue.Empty:
            conn.close_lane(req_id)
            raise WireUnavailable(
                f"engine {self.name}: no reply within "
                f"{budget:.1f}s") from None
        if got[0] == "conn_err":
            raise got[1]
        return got[1], got[2], got[3]        # kind, header, payload

    def _unary(self, op: int, header: bytes, budget: float,
               tokens=None, timeout=None, max_new=None, step=None
               ) -> Dict[str, Any]:
        from .router import EngineUnavailable
        try:
            conn, req_id, lane = self._open(op, header, tokens,
                                            timeout, max_new, step)
        except WireUnavailable as e:
            raise EngineUnavailable(str(e)) from e
        try:
            try:
                kind, _h, payload = self._wait(conn, req_id, lane,
                                               budget)
            except WireUnavailable as e:
                raise EngineUnavailable(str(e)) from e
            if kind == K_ERR:
                raise exception_for_error(*decode_error(payload),
                                          engine=self.name)
            if kind != K_RESULT:
                raise EngineUnavailable(
                    f"engine {self.name}: unexpected "
                    f"{KIND_NAMES.get(kind)} reply")
            return timed_json_loads(payload, self.stats)
        finally:
            conn.close_lane(req_id)

    # -- the engine-handle surface ------------------------------------------
    def probe(self) -> Dict[str, Any]:
        return self._unary(OP_PROBE, b"", self.connect_timeout_s)

    def stats_snapshot(self) -> Dict[str, Any]:
        return self._unary(OP_STATS, b"", self.connect_timeout_s)

    def reload(self, step: Optional[int] = None,
               trace=None) -> Dict[str, Any]:
        return self._unary(
            OP_RELOAD, encode_qos_header(trace=trace), 60.0,
            step=-1 if step is None else step)

    def request(self, mode: str, tokens,
                timeout: Optional[float] = None,
                deadline: Optional[float] = None,
                priority: Optional[str] = None,
                trace=None,
                tenant: Optional[str] = None) -> Dict[str, Any]:
        header = encode_qos_header(deadline=deadline,
                                   priority=priority, tenant=tenant,
                                   trace=trace)
        budget = qos.transport_budget(deadline, timeout,
                                      self.connect_timeout_s)
        op = OP_GENERATE if mode == "generate" else OP_PREDICT
        return self._unary(op, header, budget, tokens=tokens,
                           timeout=timeout)

    def request_stream(self, tokens, timeout: Optional[float] = None,
                       max_new: Optional[int] = None,
                       deadline: Optional[float] = None,
                       priority: Optional[str] = None,
                       resume_from: int = 0, trace=None,
                       tenant: Optional[str] = None):
        """Streaming generate over the framed connection.  Admission
        errors surface on the FIRST next() as mapped exceptions (the
        router's retry-on-other-engine commit point); after the first
        token a transport failure is a mid-stream RuntimeError the
        session layer catches and RESUMES on a sibling.  Closing the
        generator (hedge loser, abandoned failover leg) sends CANCEL
        and frees the lane — the CONNECTION survives for its other
        in-flight requests."""
        from .router import EngineUnavailable
        header = encode_qos_header(deadline=deadline,
                                   priority=priority, tenant=tenant,
                                   trace=trace,
                                   resume_from=resume_from)
        budget = qos.transport_budget(deadline, timeout,
                                      self.connect_timeout_s)

        def gen():
            try:
                conn, req_id, lane = self._open(
                    OP_STREAM, header, tokens=tokens,
                    timeout=timeout, max_new=max_new)
            except WireUnavailable as e:
                raise EngineUnavailable(str(e)) from e
            streamed = False
            finished = False
            try:
                while True:
                    try:
                        got = lane.get(timeout=max(budget, 0.1))
                    except queue.Empty:
                        raise TimeoutError(
                            f"engine {self.name} stream stalled"
                        ) from None
                    if got[0] == "conn_err":
                        if streamed:
                            e = RuntimeError(
                                f"engine {self.name} stream broken: "
                                f"{got[1]}")
                            e.wire_transport = True
                            raise e
                        raise EngineUnavailable(
                            f"engine {self.name}: {got[1]}")
                    kind, _h, payload = got[1], got[2], got[3]
                    if kind == K_TOKENS:
                        first_i, toks = decode_tokens(payload)
                        streamed = True
                        i = first_i
                        for t in toks:
                            yield {"token": int(t), "i": i}
                            i += 1
                    elif kind == K_DONE:
                        finished = True
                        yield timed_json_loads(payload, self.stats)
                        return
                    elif kind == K_ERR:
                        exc = exception_for_error(
                            *decode_error(payload), engine=self.name)
                        if streamed:
                            raise RuntimeError(
                                f"engine {self.name} stream failed: "
                                f"{exc}")
                        raise exc
                    # other kinds: version-compatible noise, skip
            finally:
                conn.close_lane(req_id)
                if not finished and conn.alive:
                    try:
                        conn.send(K_CANCEL, req_id)
                        self.stats.count("cancels_tx")
                    except (ConnectionError, OSError):
                        pass
        return gen()


# -- transport negotiation ---------------------------------------------------

class NegotiatingEngineHandle:
    """Per-engine transport negotiation with automatic HTTP fallback.
    HTTP/JSON is the always-on debug-and-control surface: probes,
    stats, and reloads ride it unconditionally, and every `probe()`
    is also the DISCOVERY point — a worker advertising `wire_port` on
    /healthz upgrades this engine's data plane (request /
    request_stream) to the binary transport.  Any transport-level
    binary failure (WireUnavailable, a broken mid-stream socket)
    degrades the engine back to HTTP — counted
    `wire_fallbacks_total` — without failing the request when a
    same-call HTTP retry is safe, and the next probe re-negotiates,
    so a restarted binary listener is re-adopted automatically."""

    def __init__(self, name: str, base_url: str,
                 connect_timeout_s: float = 5.0,
                 stats: Optional[WireStats] = None, log_fn=print):
        from .router import HttpEngineHandle
        self.name = name
        self.http = HttpEngineHandle(name, base_url,
                                     connect_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.stats = stats or STATS
        self.log = log_fn
        self._host = base_url.split("//", 1)[-1].split("/", 1)[0] \
                             .rsplit(":", 1)[0] or "127.0.0.1"
        self._lock = threading.Lock()
        self._bin: Optional[BinaryEngineHandle] = None
        self._wire_port: Optional[int] = None
        self._bin_down = False

    # -- negotiation state ---------------------------------------------------
    @property
    def transport(self) -> str:
        with self._lock:
            return ("binary" if self._wire_port and not self._bin_down
                    else "http")

    def _binary(self) -> Optional[BinaryEngineHandle]:
        with self._lock:
            if self._wire_port is None or self._bin_down:
                return None
            if self._bin is None or \
                    self._bin.address[1] != self._wire_port:
                if self._bin is not None:
                    self._bin.close()
                self._bin = BinaryEngineHandle(
                    self.name, (self._host, self._wire_port),
                    self.connect_timeout_s, stats=self.stats)
            return self._bin

    def _mark_down(self, why: str) -> None:
        with self._lock:
            if self._bin_down:
                return
            self._bin_down = True
        self.stats.count("fallbacks")
        obs.emit_event("wire.fallback", engine=self.name, why=why)
        self.log(f"warning: engine {self.name} binary transport "
                 f"down ({why}); serving over HTTP until the next "
                 f"probe re-negotiates")

    def close(self) -> None:
        with self._lock:
            if self._bin is not None:
                self._bin.close()
                self._bin = None
        self.http.close()

    # -- the engine-handle surface ------------------------------------------
    def probe(self) -> Dict[str, Any]:
        h = self.http.probe()
        port = h.get("wire_port")
        with self._lock:
            if port:
                if int(port) != self._wire_port:
                    self._wire_port = int(port)
                # every probe re-arms the upgrade: a dead listener
                # costs at most one fallback per probe period
                self._bin_down = False
            else:
                self._wire_port = None
                if self._bin is not None:
                    self._bin.close()
                    self._bin = None
        h["transport"] = self.transport
        return h

    def stats_snapshot(self) -> Dict[str, Any]:
        return self.http.stats_snapshot()

    def reload(self, step: Optional[int] = None,
               trace=None) -> Dict[str, Any]:
        return self.http.reload(step=step, trace=trace)

    def request(self, mode: str, tokens,
                timeout: Optional[float] = None,
                deadline: Optional[float] = None,
                priority: Optional[str] = None,
                trace=None,
                tenant: Optional[str] = None) -> Dict[str, Any]:
        b = self._binary()
        if b is not None:
            try:
                return b.request(mode, tokens, timeout=timeout,
                                 deadline=deadline,
                                 priority=priority, trace=trace,
                                 tenant=tenant)
            except Exception as e:  # noqa: BLE001 — fallback filter
                if not _is_transport_failure(e):
                    raise
                self._mark_down(str(e))
        return self.http.request(mode, tokens, timeout=timeout,
                                 deadline=deadline,
                                 priority=priority, trace=trace,
                                 tenant=tenant)

    def request_stream(self, tokens, timeout: Optional[float] = None,
                       max_new: Optional[int] = None,
                       deadline: Optional[float] = None,
                       priority: Optional[str] = None,
                       resume_from: int = 0, trace=None,
                       tenant: Optional[str] = None):
        """Stream over binary when negotiated, degrading to HTTP when
        admission never committed (no byte lost: the whole stream
        simply re-admits over HTTP).  A MID-stream binary death
        propagates as the usual RuntimeError — the session layer owns
        the splice, and because the failure also marks the transport
        down, the resume leg lands on HTTP."""
        kw = dict(timeout=timeout, max_new=max_new,
                  deadline=deadline, priority=priority,
                  resume_from=resume_from, trace=trace,
                  tenant=tenant)

        def gen():
            b = self._binary()
            inner = None
            if b is not None:
                inner = b.request_stream(tokens, **kw)
                try:
                    first = next(inner)
                except Exception as e:  # noqa: BLE001 — filter below
                    if not _is_transport_failure(e):
                        raise
                    self._mark_down(str(e))
                    inner = None
            if inner is None:
                inner = self.http.request_stream(tokens, **kw)
                first = next(inner)
            try:
                yield first
                for ev in inner:
                    yield ev
            except RuntimeError as e:
                if getattr(e, "wire_transport", False):
                    self._mark_down(str(e))
                raise
            finally:
                inner.close()
        return gen()


def _is_transport_failure(e: BaseException) -> bool:
    """True for failures of the binary WIRE (connect/handshake/socket
    death) where an HTTP fallback can help; False for engine-reported
    errors (Overloaded, deadline, bad request...) that would fail
    identically over HTTP."""
    if isinstance(e, WireUnavailable):
        return True
    if getattr(e, "wire_transport", False):
        return True
    cause = getattr(e, "__cause__", None)
    return isinstance(cause, WireUnavailable)


def register_into(registry, prefix: str = "singa_wire") -> None:
    """Export the process-wide wire counters into a MetricsRegistry
    (the perf.register_into mold)."""
    STATS.register_into(registry, prefix=prefix)
