"""Fleet router: health-driven dispatch over N engine workers.

One engine per process caps serving throughput at one chip's tok/s and
makes every crash a 100% outage; the router is the horizontal half of
the north star ("millions of users") and the modern answer to the
reference's ZeroMQ server pool (PAPER.md L4) — survive partial failure
by construction, the TensorFlow-paper argument (arxiv 1605.08695).

Three moving parts:

  * `EngineHandle` — the uniform worker surface.  `LocalEngineHandle`
    wraps an in-process `InferenceServer` (threads: the CPU-test and
    single-machine shape); `HttpEngineHandle` speaks to a separate
    `singa_tpu.main serve --pinned` process over its HTTP surface
    (/healthz, /stats, /generate, /predict, /admin/reload) — the
    subprocess deployment whose membership comes from
    `parallel.bootstrap.parse_hostfile`.
  * `Router` — per-request dispatch to the least-loaded healthy
    engine (in-flight + last-probed queue depth), with
    retry-on-other-engine: an engine failure (connection refused, a
    500, an injected `fleet.dispatch` fault) charges the engine a
    strike and the request moves on; the client sees a failure only
    when every admissible engine has been tried.  `Overloaded` from
    one engine is load, not failure — the request retries elsewhere
    without a strike.  When NO engine can take the request the router
    itself sheds with `Overloaded` + an escalating Backoff
    `Retry-After`, mirroring the MicroBatcher's admission story one
    level up.
  * the probe loop — every `probe_period_s` each member's
    /healthz + ServeStats are read; a degraded verdict pulls the
    engine out of dispatch (it re-enters the moment it reports ok),
    while hard probe failures accumulate strikes toward quarantine.
    Quarantine/readmission mirrors `ReplicaSet`'s poisoned-round
    policy: `quarantine_after` consecutive strikes bench the engine
    for a `utils.faults.Backoff` delay that doubles on each
    consecutive re-quarantine, and a clean probe after the bench
    readmits it (counted, evented — `fleet.quarantine` /
    `fleet.readmit`).

Rollout (canary / promote / rollback) rides on top of this in
`fleet.py`; the router only answers "who is healthy and least loaded
right now" and "move this request somewhere else".
"""

from __future__ import annotations

import dataclasses
import http.client
import inspect
import itertools
import json
import queue
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import obs
from ..utils import faults
from . import qos
from .batcher import Cancelled, DeadlineExpired, Overloaded
from .session import SessionManager
from .tenancy import TenantCounts, TenantRegistry


class EngineUnavailable(RuntimeError):
    """The chosen engine could not take the request at all (process
    dead, connection refused, handler crashed) — retried on another
    engine and charged to this one as a strike."""


class UnknownModel(ValueError):
    """The requested model family is served by NO member of the fleet:
    an honest fast rejection (the HTTP layer's 404) decided at
    admission, before any engine is picked — never a strike against an
    engine, never a shed, never a Retry-After (waiting will not make
    the family appear).  A ValueError subclass so duck-typed callers
    that predate model-aware routing still treat it as an unservable
    request, not an engine failure."""


class _FailoverStale(RuntimeError):
    """No engine pinned to the session's fingerprint remains, but
    OTHER fingerprints are serving — resuming there would break
    bit-determinism, so the stream terminates honestly with
    `finish: "failover_stale"` instead of splicing a lie."""


class LameDuck(RuntimeError):
    """The router is draining for handoff: in-flight streams finish,
    NEW admissions are refused with a Retry-After pointing at the
    successor (the HTTP layer's 409).  Not a shed — capacity exists,
    it just lives behind the successor's address now."""

    def __init__(self, msg: str, successor: Optional[str] = None,
                 retry_after: float = 0.5):
        super().__init__(msg)
        self.successor = successor
        self.retry_after = float(retry_after)


class UnknownSession(KeyError):
    """A reconnect presented a session id the router does not hold —
    never journaled, or already evicted past the retention TTL/cap.
    The HTTP layer's 410: retrying the SAME sid cannot succeed."""


@dataclass(frozen=True)
class RouterSpec:
    """Router config grammar (`--fleet_spec`, the ServeSpec mold):
    comma/semicolon-separated `key=value`."""
    probe_period_s: float = 0.25   # health-probe cadence per engine
    quarantine_after: int = 2      # consecutive strikes -> quarantine
    readmit_base_s: float = 0.25   # Backoff base for the bench time
    readmit_cap_s: float = 10.0    # Backoff cap
    max_attempts: int = 0          # engines tried per request (0 = all)
    request_timeout_s: float = 5.0
    seed: int = 0
    hedge: str = "on"              # hedged dispatch ("Tail at Scale")
    hedge_min_s: float = 0.05      # clamp on the p95-derived delay
    hedge_max_s: float = 1.0
    retry_budget_ratio: float = 0.1   # retries+hedges per primary
    retry_budget_burst: float = 16.0  # token-bucket cap
    brownout_shed_rate: float = 0.1   # capacity-shed rate engaging
                                      # brownout (0 = never)
    resume: str = "on"             # mid-stream failover: resume a
                                   # journaled stream on a sibling
                                   # ("off" = pre-PR terminal errors)
    stream_idle_s: float = 0.0     # per-stream idle watchdog: no
                                   # token for this long -> failover
                                   # (0 = off; catches engine.stall-
                                   # style silent stragglers)
    wal: str = "on"                # durable session WAL (off = the
                                   # pre-PR in-memory-only journal)
    wal_group_tokens: int = 64     # group-commit: fsync every N
    wal_group_ms: float = 25.0     # journaled records / T ms
    state_snapshot_s: float = 0.5  # control-state snapshot cadence
    session_ttl_s: float = 300.0   # terminal-session retention TTL
    session_cap: int = 1024        # ... and count cap
    flush_tokens: int = 8          # frontend token-flush batching
    flush_ms: float = 4.0          # (serve/wire.py LineCoalescer):
                                   # tokens per ndjson chunk / linger.
                                   # First token always flushes alone

    def __post_init__(self):
        if int(self.quarantine_after) < 1:
            raise ValueError(f"quarantine_after must be >= 1, got "
                             f"{self.quarantine_after}")
        if float(self.probe_period_s) <= 0:
            raise ValueError(f"probe_period_s must be > 0, got "
                             f"{self.probe_period_s}")
        if str(self.hedge) not in ("on", "off"):
            raise ValueError(f"hedge must be on|off, got {self.hedge!r}")
        if not (0 < float(self.hedge_min_s) <= float(self.hedge_max_s)):
            raise ValueError(
                f"need 0 < hedge_min_s <= hedge_max_s, got "
                f"{self.hedge_min_s}/{self.hedge_max_s}")
        if float(self.retry_budget_ratio) < 0 or \
                float(self.retry_budget_burst) < 0:
            raise ValueError("retry budget ratio/burst must be >= 0")
        if str(self.resume) not in ("on", "off"):
            raise ValueError(f"resume must be on|off, got "
                             f"{self.resume!r}")
        if float(self.stream_idle_s) < 0:
            raise ValueError(f"stream_idle_s must be >= 0, got "
                             f"{self.stream_idle_s}")
        if str(self.wal) not in ("on", "off"):
            raise ValueError(f"wal must be on|off, got {self.wal!r}")
        if int(self.wal_group_tokens) < 1:
            raise ValueError(f"wal_group_tokens must be >= 1, got "
                             f"{self.wal_group_tokens}")
        if float(self.wal_group_ms) < 0 or \
                float(self.state_snapshot_s) <= 0:
            raise ValueError("wal_group_ms must be >= 0 and "
                             "state_snapshot_s > 0")
        if float(self.session_ttl_s) < 0 or int(self.session_cap) < 0:
            raise ValueError("session_ttl_s/session_cap must be >= 0")
        if int(self.flush_tokens) < 1 or float(self.flush_ms) < 0:
            raise ValueError("flush_tokens must be >= 1 and flush_ms "
                             ">= 0")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "RouterSpec":
        kw: Dict[str, Any] = {}
        types = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key, sep, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or key not in types:
                    raise ValueError(f"unknown key {key!r}")
                if "str" in str(types[key]):
                    kw[key] = val.lower()
                else:
                    kw[key] = (float(val)
                               if "float" in str(types[key])
                               else int(val))
            except ValueError as e:
                raise ValueError(f"bad fleet spec entry {part!r} "
                                 f"(want key=value): {e}") from e
        return cls(**kw)


# signature cache for duck-typed handles: tests (and future adapters)
# plug in handles whose request() predates deadlines/priorities — the
# router forwards only the keywords each handle actually accepts
_SIG_CACHE: Dict[Any, Optional[frozenset]] = {}


def _accepted_kwargs(fn) -> Optional[frozenset]:
    key = getattr(fn, "__func__", fn)
    if key not in _SIG_CACHE:
        try:
            params = inspect.signature(key).parameters
            if any(p.kind == inspect.Parameter.VAR_KEYWORD
                   for p in params.values()):
                _SIG_CACHE[key] = None       # **kwargs: takes anything
            else:
                _SIG_CACHE[key] = frozenset(params)
        except (TypeError, ValueError):
            _SIG_CACHE[key] = None
    return _SIG_CACHE[key]


def _handle_call(fn, args: tuple, kwargs: Dict[str, Any]):
    accepted = _accepted_kwargs(fn)
    if accepted is not None:
        kwargs = {k: v for k, v in kwargs.items() if k in accepted}
    return fn(*args, **kwargs)


# -- engine handles ---------------------------------------------------------

class LocalEngineHandle:
    """In-process worker: a pinned `InferenceEngine` + `MicroBatcher`
    wrapped in an `InferenceServer` (no HTTP — the router IS the
    frontend).  `kill()`/`revive()` give tests and the bench a
    deterministic crash/recovery lever."""

    def __init__(self, name: str, server):
        self.name = name
        self.server = server          # serve.InferenceServer
        self.engine = server.engine
        self._alive = True

    def start(self) -> None:
        self.server.start()
        self._alive = True

    def stop(self) -> None:
        self._alive = False
        self.server.stop()

    def kill(self) -> None:
        """Simulate a worker crash: requests and probes fail until
        revive()."""
        self._alive = False
        self.server.stop()

    def revive(self) -> None:
        self.server.start()
        self._alive = True

    def probe(self) -> Dict[str, Any]:
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        h = dict(self.engine.health())
        h["queue_depth"] = self.engine.stats.queue_depth
        return h

    def stats_snapshot(self) -> Dict[str, Any]:
        return self.server.snapshot()

    def request(self, mode: str, tokens,
                timeout: Optional[float] = None,
                deadline: Optional[float] = None,
                priority: str = "interactive",
                cancel_event: Optional[threading.Event] = None,
                tenant: str = "default") -> Dict[str, Any]:
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        call = (self.server.generate if mode == "generate"
                else self.server.predict)
        try:
            return call(tokens, timeout=timeout, deadline=deadline,
                        priority=priority, cancel_event=cancel_event,
                        tenant=tenant)
        except (Overloaded, DeadlineExpired, TimeoutError, ValueError,
                Cancelled):
            raise
        except Exception as e:  # noqa: BLE001 — batch failed / stopped
            raise EngineUnavailable(
                f"engine {self.name} failed: {e}") from e

    def request_stream(self, tokens, timeout: Optional[float] = None,
                       max_new: Optional[int] = None,
                       deadline: Optional[float] = None,
                       priority: str = "interactive",
                       cancel_event: Optional[threading.Event] = None,
                       resume_from: int = 0,
                       tenant: str = "default"):
        """Streaming generate (cb engines only).  Admission happens
        HERE, before any event is yielded — the router's commit point
        for retry-on-other-engine.  Returns an iterator of ndjson-
        shaped dicts: {"token": t, "i": n} per token (n the absolute
        sequence number, resume_from-based for a failover
        re-admission), then the final {"done": True, ...} summary."""
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        try:
            ticket = self.server.generate_stream(
                tokens, timeout=timeout, max_new=max_new,
                deadline=deadline, priority=priority,
                cancel_event=cancel_event, resume_from=resume_from,
                tenant=tenant)
        except (Overloaded, DeadlineExpired, TimeoutError, ValueError,
                Cancelled):
            raise
        except Exception as e:  # noqa: BLE001 — no cb / stopped
            raise EngineUnavailable(
                f"engine {self.name} cannot stream: {e}") from e
        budget = qos.transport_budget(
            deadline, timeout, self.engine.spec.request_timeout_s)

        def gen():
            # in-process hot path: drain the ticket in BATCHES (one
            # queue round-trip per flush_tokens instead of per token)
            # and stage them through a shared-memory TokenRing — raw
            # int32s end to end, nothing serialized, zero bytes
            # copied out of the ring's buffer (serve/wire.py).  The
            # first token drains alone: first-token latency is a
            # gated stage and must not pay for batching
            from . import wire as _wire
            spec = self.engine.spec
            flush_n = max(int(getattr(spec, "flush_tokens", 8)), 1)
            linger = max(float(getattr(spec, "flush_ms", 4.0)),
                         0.0) / 1000.0
            ring = _wire.TokenRing(max(flush_n * 8, 64))
            i = ticket.first_index
            first = True
            while True:
                evs = ticket.drain_events(
                    max_n=1 if first else flush_n,
                    timeout=budget,
                    linger_s=0.0 if first else linger)
                first = False
                toks = [p for k, p in evs if k == "tok"]
                if toks:
                    ring.push_many(toks)
                    left = len(toks)
                    while left > 0:
                        _k, _start, view = ring.peek_batch(left)
                        for t in view:
                            yield {"token": int(t), "i": i}
                            i += 1
                        ring.consume(len(view))
                        left -= len(view)
                    _wire.STATS.count("token_flushes")
                tail = evs[-1]
                if tail[0] == "tok":
                    continue
                if tail[0] == "failed":
                    raise tail[1]
                out = dict(tail[1])
                out["done"] = True
                yield out
                return
        return gen()

    def reload(self, step: Optional[int] = None) -> Dict[str, Any]:
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        outcome = self.engine.reload_to(step)
        return {"outcome": outcome, "step": self.engine.params_step}


class HttpEngineHandle:
    """Worker behind a URL: a `singa_tpu.main serve --pinned` process
    (membership from a hostfile).  Maps the server's status codes back
    to the router's exception vocabulary.

    Unary calls and probes ride a small keep-alive connection pool:
    opening a fresh TCP connection per request put connection setup on
    the hot path (and under probe cadence, several times a second per
    engine).  A pooled connection is returned after a clean
    keep-alive exchange and DISCARDED on any error — a socket that
    failed once is never trusted again.  Streams keep their own
    dedicated connections: a stream owns its socket for its lifetime,
    pooling it would just serialize streams behind each other."""

    #: pooled sockets per handle — enough for probe + a hedged pair
    POOL_CAP = 4

    def __init__(self, name: str, base_url: str,
                 connect_timeout_s: float = 5.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.connect_timeout_s = connect_timeout_s
        netloc = self.base_url.split("//", 1)[-1].split("/", 1)[0]
        host, _, port = netloc.partition(":")
        self._host, self._port = host or "127.0.0.1", int(port or 80)
        self._pool: deque = deque()
        self._pool_lock = threading.Lock()

    def _acquire_conn(self, timeout: float):
        """(connection, was_reused) — pop a pooled keep-alive socket
        or dial a fresh one."""
        with self._pool_lock:
            if self._pool:
                c = self._pool.popleft()
                if c.sock is not None:
                    c.sock.settimeout(timeout)
                return c, True
        c = http.client.HTTPConnection(self._host, self._port,
                                       timeout=timeout)
        return c, False

    def _release_conn(self, conn, reusable: bool) -> None:
        if reusable:
            with self._pool_lock:
                if len(self._pool) < self.POOL_CAP:
                    self._pool.append(conn)
                    return
        conn.close()

    def close(self) -> None:
        """Drop every pooled socket (fleet teardown)."""
        with self._pool_lock:
            conns, self._pool = list(self._pool), deque()
        for c in conns:
            c.close()

    def _call(self, method: str, path: str,
              payload: Optional[dict] = None,
              timeout: Optional[float] = None,
              headers: Optional[Dict[str, str]] = None
              ) -> Dict[str, Any]:
        data = (json.dumps(payload).encode()
                if payload is not None else None)
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        budget = timeout or self.connect_timeout_s
        for attempt in (0, 1):
            conn, reused = self._acquire_conn(budget)
            try:
                conn.request(method, path, body=data, headers=hdrs)
                r = conn.getresponse()
                # drain the body BEFORE judging the status: an error
                # reply is a socket too, and under retry/hedge churn
                # leaving it to GC leaks one fd per failed call (the
                # fd-flat regression test in test_router_wal.py
                # watches this).  A fully-read keep-alive exchange —
                # success or mapped error — leaves the socket reusable
                body_bytes = r.read()
            except (http.client.HTTPException, ConnectionError,
                    OSError) as e:
                conn.close()
                if reused and attempt == 0:
                    # the stale keep-alive race: the peer closed this
                    # idle socket between our calls, nothing was
                    # processed — retry once on a FRESH connection
                    continue
                raise EngineUnavailable(
                    f"engine {self.name} unreachable: {e}") from e
            self._release_conn(conn, reusable=not r.will_close)
            body = {}
            try:
                body = json.loads(body_bytes)
            except Exception:  # noqa: BLE001 — non-JSON error body
                pass
            code = r.status
            if code == 200:
                return body
            if code == 503 and path == "/healthz":
                return body or {"ok": False, "status": "degraded"}
            if code == 503:
                raise Overloaded(
                    body.get("error", "overloaded"),
                    retry_after=float(body.get("retry_after", 0.0)))
            if code == 504:
                raise DeadlineExpired(body.get("error", "deadline"))
            if code == 400:
                raise ValueError(body.get("error", "bad request"))
            raise EngineUnavailable(
                f"engine {self.name}: HTTP {code} "
                f"{body.get('error', '')}")
        raise EngineUnavailable(f"engine {self.name} unreachable")

    def probe(self) -> Dict[str, Any]:
        h = self._call("GET", "/healthz")
        try:
            snap = self._call("GET", "/stats")
            h["queue_depth"] = snap.get("queue_depth", 0)
        except EngineUnavailable:
            h["queue_depth"] = 0
        return h

    def stats_snapshot(self) -> Dict[str, Any]:
        return self._call("GET", "/stats")

    @staticmethod
    def _qos_headers(deadline: Optional[float],
                     priority: Optional[str],
                     trace=None,
                     tenant: Optional[str] = None) -> Dict[str, str]:
        """End-to-end propagation over the wire: remaining-ms deadline
        header (re-anchored by the receiver), priority class, tenant
        id (`X-Tenant`), and the `X-Trace-Id`/`X-Parent-Span` pair —
        the worker's spans anchor under the router's attempt span in
        the merged trace."""
        hdrs: Dict[str, str] = {}
        dl = qos.deadline_to_header(deadline)
        if dl is not None:
            hdrs[qos.DEADLINE_HEADER] = dl
        if priority is not None:
            hdrs[qos.PRIORITY_HEADER] = str(priority)
        if tenant is not None:
            hdrs[qos.TENANT_HEADER] = str(tenant)
        hdrs.update(qos.trace_to_headers(trace))
        return hdrs

    def request(self, mode: str, tokens,
                timeout: Optional[float] = None,
                deadline: Optional[float] = None,
                priority: Optional[str] = None,
                trace=None,
                tenant: Optional[str] = None) -> Dict[str, Any]:
        toks = (tokens.tolist() if isinstance(tokens, np.ndarray)
                else list(tokens))
        payload = {"tokens": [int(t) for t in toks]}
        if timeout is not None:
            payload["timeout"] = timeout
        budget = qos.transport_budget(deadline, timeout,
                                      self.connect_timeout_s)
        return self._call("POST", f"/{mode}", payload, timeout=budget,
                          headers=self._qos_headers(deadline, priority,
                                                    trace, tenant))

    def request_stream(self, tokens, timeout: Optional[float] = None,
                       max_new: Optional[int] = None,
                       deadline: Optional[float] = None,
                       priority: Optional[str] = None,
                       resume_from: int = 0, trace=None,
                       tenant: Optional[str] = None):
        """Streaming generate over HTTP: POST {"stream": true} and
        decode the chunked ndjson line-by-line WITHOUT buffering the
        body.  The response status is the commit point: admission
        errors surface as mapped exceptions before any line is
        yielded; after that a transport failure is a mid-stream
        RuntimeError — which the router's session layer now catches
        and RESUMES on a sibling engine (`resume_from` carries the
        journaled-prefix length on a re-admission)."""
        toks = (tokens.tolist() if isinstance(tokens, np.ndarray)
                else list(tokens))
        payload: Dict[str, Any] = {"tokens": [int(t) for t in toks],
                                   "stream": True}
        if timeout is not None:
            payload["timeout"] = timeout
        if max_new is not None:
            payload["max_new"] = int(max_new)
        if int(resume_from) > 0:
            payload["resume_from"] = int(resume_from)
        budget = qos.transport_budget(deadline, timeout,
                                      self.connect_timeout_s)
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(self._qos_headers(deadline, priority, trace,
                                      tenant))
        req = urllib.request.Request(
            f"{self.base_url}/generate",
            data=json.dumps(payload).encode(), method="POST",
            headers=hdrs)
        try:
            resp = urllib.request.urlopen(req, timeout=budget)
        except urllib.error.HTTPError as e:
            # same fd discipline as _call: the error response is a
            # socket — close it before mapping the status
            body = {}
            try:
                body = json.loads(e.read())
            except Exception:  # noqa: BLE001 — non-JSON error body
                pass
            finally:
                e.close()
            if e.code == 503:
                raise Overloaded(
                    body.get("error", "overloaded"),
                    retry_after=float(body.get("retry_after", 0.0)))
            if e.code == 504:
                raise DeadlineExpired(body.get("error", "deadline"))
            if e.code == 400:
                raise ValueError(body.get("error", "bad request"))
            raise EngineUnavailable(
                f"engine {self.name}: HTTP {e.code} "
                f"{body.get('error', '')}")
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise EngineUnavailable(
                f"engine {self.name} unreachable: {e}") from e

        def gen():
            try:
                for line in resp:
                    line = line.strip()
                    if not line:
                        continue
                    ev = json.loads(line)
                    if "error" in ev and "done" not in ev:
                        raise RuntimeError(
                            f"engine {self.name} stream failed: "
                            f"{ev['error']}")
                    yield ev
            except (urllib.error.URLError, ConnectionError,
                    OSError) as e:
                raise RuntimeError(
                    f"engine {self.name} stream broken: {e}") from e
            finally:
                # unconditional teardown: a hedge loser's gen.close()
                # or a failover abandon lands here via GeneratorExit,
                # and the socket dies WITH the generator — never
                # parked on the GC under churn
                resp.close()
        return gen()

    def reload(self, step: Optional[int] = None,
               trace=None) -> Dict[str, Any]:
        return self._call("POST", "/admin/reload", {"step": step},
                          timeout=60.0,
                          headers=qos.trace_to_headers(trace))


# -- router -----------------------------------------------------------------

@dataclass
class _Member:
    handle: Any
    healthy: bool = True          # last probe verdict (soft: re-enters
    step: int = -1                # on the next ok probe)
    family: str = "default"       # checkpoint family advertised on
                                  # /healthz: the fingerprint namespace
                                  # is (family, step)
    queue_depth: int = 0
    in_flight: int = 0
    strikes: int = 0              # consecutive probe/dispatch failures
    quarantined: bool = False
    quarantines: int = 0          # lifetime count (drives the Backoff)
    bench_until: float = 0.0      # monotonic readmission-probe time
    dispatched: int = 0
    failed: int = 0
    draining: bool = False        # retiring: no new admissions, pops
    last_health: Dict[str, Any] = field(default_factory=dict)  # when drained


class RouterStats:
    """Aggregate router counters (RouterStats ≈ the fleet-level
    ServeStats; per-engine detail lives in Router.members()).

    Beside the lifetime counters, `windowed()` reports rates over the
    last `window_s` seconds — the autoscaler's control inputs.  A
    cumulative shed counter can't distinguish "shed a lot at 9am" from
    "shedding right now"; the windowed view can."""

    FIELDS = ("routed", "completed", "retried", "failed", "shed",
              "quarantines", "readmissions", "joins", "retires",
              "attempts", "hedges", "hedge_wins", "deadline_terminal",
              "expired_on_arrival", "budget_denied", "brownout_sheds",
              "shed_interactive", "shed_batch", "shed_best_effort",
              "unknown_model", "lame_duck_refusals")

    #: per-request lifecycle stages the router can time (the stage
    #: taxonomy in docs/OBSERVABILITY.md); each gets its own
    #: `singa_request_stage_seconds_<stage>` histogram
    STAGES = ("admit", "dispatch", "first_token", "decode")

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._latencies: List[float] = []
        self._t0 = time.monotonic()
        self._routed_t: deque = deque(maxlen=16384)   # (stamp, tenant)
        self._shed_t: deque = deque(maxlen=16384)     # (stamp, priority,
                                                      #  brownout, tenant)
        self._done_t: deque = deque(maxlen=16384)     # (stamp, latency,
                                                      #  priority, tenant)
        # per-tenant lifetime accounting (bounded label set; callers
        # pass registry-FOLDED labels) — exported as singa_tenant_*
        self.tenants = TenantCounts(("routed", "completed", "shed"))
        # owned histogram handles, attached by register_into (None
        # without a registry — observe_latency/observe_stage stay
        # cheap no-ops on the histogram half)
        self._hist_latency = None
        self._stage_registry = None
        self._stage_hists: Dict[str, Any] = {}

    def count(self, fieldname: str, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            setattr(self, fieldname, getattr(self, fieldname) + n)
            if fieldname == "routed":
                self._routed_t.extend([(now, "default")] * n)
            elif fieldname == "shed":
                self._shed_t.extend(
                    [(now, "interactive", False, "default")] * n)

    def observe_routed(self, tenant: str = "default",
                       n: int = 1) -> None:
        """One admitted request, attributed to its tenant (the
        tenant-aware twin of `count("routed")`)."""
        now = time.monotonic()
        with self._lock:
            self.routed += n
            self._routed_t.extend([(now, tenant)] * n)
        self.tenants.count("routed", tenant, n)

    def observe_shed(self, priority: str = "interactive",
                     brownout: bool = False, n: int = 1,
                     tenant: str = "default") -> None:
        """One shed, attributed to its class and tenant.
        `brownout=False` is a CAPACITY shed (nothing could take the
        request) — the pressure signal that engages brownout; brownout
        sheds themselves are excluded from it, or shedding would keep
        brownout engaged forever (positive feedback)."""
        now = time.monotonic()
        with self._lock:
            self.shed += n
            setattr(self, f"shed_{priority}",
                    getattr(self, f"shed_{priority}") + n)
            if brownout:
                self.brownout_sheds += n
            self._shed_t.extend([(now, priority, brownout, tenant)] * n)
        self.tenants.count("shed", tenant, n)

    def observe_latency(self, seconds: float,
                        priority: str = "interactive",
                        tenant: str = "default") -> None:
        with self._lock:
            self._latencies.append(seconds)
            if len(self._latencies) > 4096:
                del self._latencies[:2048]
            self._done_t.append((time.monotonic(), seconds, priority,
                                 tenant))
        self.tenants.count("completed", tenant)
        self.tenants.observe_latency(seconds, tenant)
        h = self._hist_latency
        if h is not None:
            h.observe(float(seconds))

    def observe_stage(self, stage: str, seconds: float) -> None:
        """One stage timing of a finished request.  Stage histograms
        are created lazily in the registry attached by register_into
        (no registry: no-op) — the stage partition shares the e2e
        clock and its boundary stamps, so per-request stages sum to
        the request's latency by construction."""
        reg = self._stage_registry
        if reg is None:
            return
        h = self._stage_hists.get(stage)
        if h is None:
            # idempotent: registry.histogram returns the same object
            # for the same name, so a lost race costs nothing
            h = reg.histogram(
                f"singa_request_stage_seconds_{stage}",
                f"per-request wall time in stage {stage!r}")
            self._stage_hists[stage] = h
        h.observe(float(seconds))

    def windowed(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """Rates over the trailing window (capped at uptime so a
        young process isn't diluted toward zero)."""
        now = time.monotonic()
        with self._lock:
            window = float(window_s if window_s is not None
                           else self.window_s)
            window = min(window, max(now - self._t0, 1e-6))
            cut = now - window
            routed_rows = [tn for t, tn in self._routed_t if t >= cut]
            sheds = [(p, b, tn) for t, p, b, tn in self._shed_t
                     if t >= cut]
            done = [(l, p, tn) for t, l, p, tn in self._done_t
                    if t >= cut]
        routed = len(routed_rows)
        lats = sorted(l for l, _, _ in done)
        shed = len(sheds)
        capacity_shed = sum(1 for _, b, _ in sheds if not b)

        def q(frac, xs=None):
            xs = lats if xs is None else xs
            if not xs:
                return None
            return round(
                xs[min(int(frac * len(xs)), len(xs) - 1)] * 1e3, 3)
        shed_by_class = {p: 0 for p in qos.PRIORITIES}
        for p, _, _ in sheds:
            shed_by_class[p] = shed_by_class.get(p, 0) + 1
        completed_by_class = {p: 0 for p in qos.PRIORITIES}
        p95_by_class: Dict[str, Optional[float]] = {}
        for pri in qos.PRIORITIES:
            cls = sorted(l for l, p, _ in done if p == pri)
            completed_by_class[pri] = len(cls)
            p95_by_class[pri] = q(0.95, cls)
        # per-tenant window views: the autoscaler's quota-weighted
        # shed signal and the router's per-tenant brownout pressure
        tenant_labels = sorted(
            set(routed_rows)
            | {tn for _, _, tn in sheds}
            | {tn for _, _, tn in done})
        routed_by_tenant = {tn: 0 for tn in tenant_labels}
        for tn in routed_rows:
            routed_by_tenant[tn] += 1
        shed_by_tenant = {tn: 0 for tn in tenant_labels}
        capacity_shed_by_tenant = {tn: 0 for tn in tenant_labels}
        for _, b, tn in sheds:
            shed_by_tenant[tn] += 1
            if not b:
                capacity_shed_by_tenant[tn] += 1
        completed_by_tenant = {tn: 0 for tn in tenant_labels}
        p95_by_tenant: Dict[str, Optional[float]] = {}
        for tn in tenant_labels:
            tls = sorted(l for l, _, t2 in done if t2 == tn)
            completed_by_tenant[tn] = len(tls)
            p95_by_tenant[tn] = q(0.95, tls)
        capacity_shed_rate_by_tenant = {
            tn: round(capacity_shed_by_tenant[tn]
                      / max(routed_by_tenant.get(tn, 0), 1), 4)
            for tn in tenant_labels}
        return {
            "window_s": round(window, 3),
            "routed": routed,
            "shed": shed,
            "completed": len(lats),
            "qps": round(len(lats) / window, 3),
            "shed_rate": round(shed / max(routed, 1), 4),
            "capacity_shed_rate": round(
                capacity_shed / max(routed, 1), 4),
            "p50_latency_ms": q(0.5),
            "p95_latency_ms": q(0.95),
            "p99_latency_ms": q(0.99),
            "shed_by_class": shed_by_class,
            "completed_by_class": completed_by_class,
            "p95_by_class": p95_by_class,
            "routed_by_tenant": routed_by_tenant,
            "shed_by_tenant": shed_by_tenant,
            "completed_by_tenant": completed_by_tenant,
            "p95_by_tenant": p95_by_tenant,
            "capacity_shed_rate_by_tenant":
                capacity_shed_rate_by_tenant,
        }

    def latency_quantile(self, q: float) -> Optional[float]:
        with self._lock:
            lats = sorted(self._latencies)
        if not lats:
            return None
        return lats[min(int(q * len(lats)), len(lats) - 1)]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
        p50, p95, p99 = (self.latency_quantile(0.5),
                         self.latency_quantile(0.95),
                         self.latency_quantile(0.99))
        out["p50_latency_ms"] = (round(p50 * 1e3, 3)
                                 if p50 is not None else None)
        out["p95_latency_ms"] = (round(p95 * 1e3, 3)
                                 if p95 is not None else None)
        out["p99_latency_ms"] = (round(p99 * 1e3, 3)
                                 if p99 is not None else None)
        win = self.windowed()
        out["qps_recent"] = win["qps"]
        out["shed_rate_recent"] = win["shed_rate"]
        out["p95_latency_recent_ms"] = win["p95_latency_ms"]
        out["p99_latency_recent_ms"] = win["p99_latency_ms"]
        out["by_tenant"] = self.tenants.snapshot()
        return out

    def register_into(self, registry,
                      prefix: str = "singa_fleet") -> None:
        from ..obs.metrics import Sample

        # owned histograms beside the scalar collectors: the quantile
        # gauges below are point estimates a scraper cannot aggregate
        # across routers; cumulative buckets + _sum/_count can be
        self._hist_latency = registry.histogram(
            f"{prefix}_request_latency_seconds",
            "end-to-end fleet request latency (seconds)")
        self._stage_registry = registry

        def collect():
            snap = self.snapshot()
            out = [Sample(f"{prefix}_{k}_total", "counter",
                          f"fleet router counter {k!r}",
                          float(snap[k])) for k in self.FIELDS]
            out += [Sample(f"{prefix}_{k}", "gauge",
                           f"fleet router gauge {k!r}", float(snap[k]))
                    for k in ("p50_latency_ms", "p95_latency_ms",
                              "p99_latency_ms", "qps_recent",
                              "shed_rate_recent",
                              "p95_latency_recent_ms",
                              "p99_latency_recent_ms")
                    if snap.get(k) is not None]
            return out

        registry.register_collector(collect)
        self.tenants.register_into(registry)


class RequestLog:
    """Per-request lifecycle records backing `GET /debug/requests`: a
    bounded last-N ring plus the slowest-N ever seen, each row
    carrying the corr/trace ids, the serving engine, per-stage
    timings, and the leg story (hedged / resumes) — the post-mortem
    index into the merged fleet trace (docs/OBSERVABILITY.md)."""

    def __init__(self, keep: int = 64, slowest: int = 16):
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=max(int(keep), 1))
        self._slowest: List[Dict[str, Any]] = []
        self._slowest_n = max(int(slowest), 1)
        self.recorded = 0

    def record(self, **rec) -> None:
        rec.setdefault("ts", round(time.time(), 6))
        with self._lock:
            self.recorded += 1
            self._recent.append(rec)
            self._slowest.append(rec)
            self._slowest.sort(
                key=lambda r: -(r.get("latency_ms") or 0.0))
            del self._slowest[self._slowest_n:]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"recorded": self.recorded,
                    "recent": list(self._recent),
                    "slowest": list(self._slowest)}


class Router:
    """See module docstring.  Thread-safe: frontend threads call
    `route`, one daemon thread runs `_probe_loop`, and the rollout
    controller reads `members()` / calls `handle_for`."""

    def __init__(self, handles: List[Any],
                 spec: Optional[RouterSpec] = None, log_fn=print,
                 tenancy: Optional[TenantRegistry] = None):
        if not handles:
            raise ValueError("Router needs at least one engine handle")
        names = [h.name for h in handles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate engine names: {names}")
        self.spec = spec or RouterSpec()
        self.log = log_fn
        self.stats = RouterStats()
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {
            h.name: _Member(handle=h) for h in handles}
        self._backoff = faults.Backoff(base=self.spec.readmit_base_s,
                                       cap=self.spec.readmit_cap_s,
                                       seed=self.spec.seed)
        # per-(tenant, class) shed Retry-After (the old single-class
        # backoff is the default tenant's interactive stream)
        self._shed_backoffs = qos.ClassBackoffs(base=0.05, cap=2.0,
                                                seed=self.spec.seed + 1)
        # global retry budget: retries AND hedges draw from it
        self.retry_budget = qos.RetryBudget(
            ratio=self.spec.retry_budget_ratio,
            burst=self.spec.retry_budget_burst)
        # per-tenant QoS envelopes: every retry/hedge/resume charges
        # the REQUESTING tenant's child budget (floor first, then the
        # shared bucket) — an unconfigured registry is all-default,
        # which degenerates to the pre-tenancy global arithmetic
        self.tenancy = tenancy or TenantRegistry()
        self.tenancy.bind_budgets(self.retry_budget)
        # durable stream sessions: the journal mid-stream failover
        # resumes from (serve/session.py)
        self.sessions = SessionManager()
        # crash-safe control plane (serve/sessionlog.py): the fleet
        # wires a SessionWal + epoch in via attach_wal before traffic;
        # epoch 0 = no durability (the pre-PR in-memory-only shape)
        self.wal = None
        self.epoch = 0
        # lame-duck drain for zero-downtime handoff: non-None refuses
        # NEW admissions (LameDuck -> HTTP 409 + Retry-After at the
        # successor) while in-flight streams finish
        self.lame_duck: Optional[Dict[str, Any]] = None
        # per-request lifecycle records (GET /debug/requests)
        self.requests = RequestLog()
        # router-minted correlation ids for requests arriving without
        # one (an in-process caller outside any span)
        self._corr_ids = itertools.count(1)
        # cached control signals (recomputed at most every 0.5s: the
        # deques behind windowed() are too big for the hot path)
        self._hedge_cache: float = float(self.spec.hedge_max_s)
        self._hedge_cache_t: float = 0.0
        self._pressure: float = 0.0
        self._pressure_by_tenant: Dict[str, float] = {}
        self._pressure_t: float = 0.0
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Router":
        self.probe_all()              # first verdicts before traffic
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-probe", daemon=True)
        self._probe_thread.start()
        return self

    def stop(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(5.0)
            self._probe_thread = None

    # -- crash-safe control plane -------------------------------------------
    def attach_wal(self, wal, epoch: int) -> None:
        """Wire the durable session journal in (fleet does this
        BEFORE traffic): every open/token/resume/close is
        write-ahead journaled, and fresh sids are minted under
        `epoch` so a restarted router can never collide with a
        journaled predecessor's ids."""
        self.wal = wal
        self.epoch = int(epoch)
        self.sessions.configure(wal=wal, epoch=epoch,
                                ttl_s=self.spec.session_ttl_s,
                                cap=self.spec.session_cap)

    def enter_lame_duck(self, successor: Optional[str] = None,
                        retry_after: float = 0.5) -> None:
        self.lame_duck = {"successor": successor,
                          "retry_after": float(retry_after)}
        self.log(f"fleet: router entering lame-duck drain "
                 f"(successor: {successor or 'unannounced'})")

    def _check_lame_duck(self) -> None:
        ld = self.lame_duck
        if ld is None:
            return
        self.stats.count("lame_duck_refusals")
        raise LameDuck(
            "router is draining for handoff; new admissions go to "
            f"the successor ({ld['successor'] or 'see Retry-After'})",
            successor=ld["successor"], retry_after=ld["retry_after"])

    def export_control_state(self) -> Dict[str, Any]:
        """The slow-moving control state worth surviving a restart:
        quarantine strikes/benches (remaining seconds — monotonic
        stamps do not cross processes), and the per-(tenant, class)
        Retry-After streaks.  Rollout/autoscaler state merges in one
        level up (fleet.py owns those objects)."""
        now = time.monotonic()
        with self._lock:
            members = {n: {
                "strikes": m.strikes,
                "quarantined": m.quarantined,
                "quarantines": m.quarantines,
                "bench_remaining_s": round(
                    max(m.bench_until - now, 0.0), 4),
                "draining": m.draining,
            } for n, m in self._members.items()}
        return {"members": members,
                "shed_streaks": self._shed_backoffs.export_streaks()}

    def restore_control_state(self,
                              state: Optional[Dict[str, Any]]) -> None:
        """Re-apply a snapshot by engine NAME (runs after start()'s
        first probe round): a pre-crash quarantined engine stays
        benched for its REMAINING bench time — `_probe_one` skips
        benched members, so restart cannot launder a strike streak."""
        if not state:
            return
        now = time.monotonic()
        restored = []
        with self._lock:
            for n, rec in (state.get("members") or {}).items():
                m = self._members.get(n)
                if m is None:
                    continue          # membership changed: skip
                m.strikes = max(int(rec.get("strikes", 0)), m.strikes)
                m.quarantines = max(int(rec.get("quarantines", 0)),
                                    m.quarantines)
                if rec.get("quarantined"):
                    m.quarantined = True
                    m.healthy = False
                    m.bench_until = now + float(
                        rec.get("bench_remaining_s", 0.0))
                    restored.append(n)
        self._shed_backoffs.restore_streaks(
            state.get("shed_streaks") or {})
        if restored:
            self.log(f"fleet: restored quarantine benches for "
                     f"{restored} from control-state snapshot")

    def recover_sessions(self, reduced: Dict[str, Dict[str, Any]],
                         timeout: Optional[float] = None
                         ) -> Dict[str, int]:
        """Re-admit every journaled stream from a predecessor's WAL
        replay.  Finished streams re-register as replay-only terminal
        records (a no-op — no engine re-decodes them); live ones
        re-enter the existing `resume_from` path pinned to their
        journaled fingerprint and decode into the replay buffer a
        reconnecting client drains exactly-once."""
        out = {"terminal": 0, "recovered": 0, "failed": 0}
        for sid in sorted(reduced):
            rec = reduced[sid]
            try:
                if rec.get("terminal") is not None:
                    self.sessions.register_terminal(rec)
                    out["terminal"] += 1
                else:
                    self.recover_stream(rec, timeout=timeout)
                    out["recovered"] += 1
            except Exception as e:  # noqa: BLE001 — recovery is
                out["failed"] += 1  # per-stream best-effort
                self.log(f"fleet: recovery of stream {sid} failed: "
                         f"{type(e).__name__}: {e}")
        return out

    def recover_stream(self, rec: Dict[str, Any],
                       timeout: Optional[float] = None):
        """Re-admit ONE journaled live stream: open a session under
        the journaled sid with the journaled prefix (re-journaling
        both into THIS epoch's WAL, so it is self-contained), then
        drive the ordinary `_session_stream` consumer — entering via
        its recovery arm, which admits a resume leg pinned to the
        journaled fingerprint — into the session's replay buffer on a
        daemon thread.  The deadline is re-anchored fresh: the
        original died with the crash, and recovery owes the client
        its journaled tokens either way."""
        timeout = (float(timeout) if timeout is not None
                   else self.spec.request_timeout_s)
        deadline = qos.resolve_deadline(
            timeout, None, self.spec.request_timeout_s)
        priority = str(rec.get("priority") or "interactive")
        tenant = self.tenancy.label(rec.get("tenant"))
        session = self.sessions.open(
            prompt=np.asarray(rec.get("prompt") or [], np.int32),
            max_new=rec.get("max_new"), deadline=deadline,
            priority=priority, engine=rec.get("engine") or "",
            step=int(rec.get("step", -1)), tenant=tenant,
            family=rec.get("family"), sid=rec["sid"],
            emitted=rec.get("emitted"))
        session.attachable = True
        session.resumes = int(rec.get("resumes", 0))
        # seed the replay buffer with the journaled prefix: a client
        # that reconnects with resume_from=0 (lost everything) is owed
        # the WHOLE stream, not just the post-splice tail — attach()
        # drops indices below resume_from, so clients that kept their
        # prefix skip these for free
        for i, t in enumerate(session.emitted):
            session.replay_append({"token": int(t), "i": i,
                                   "sid": session.sid})
        self.stats.observe_routed(tenant)
        err = EngineUnavailable(
            f"router restarted under epoch {self.epoch}; "
            f"re-admitting journaled stream {session.sid}")
        gen = self._session_stream(session, None, time.monotonic(),
                                   priority, timeout, initial_err=err)

        def drive():
            try:
                for ev in gen:
                    session.replay_append(ev)
            except BaseException as e:  # noqa: BLE001 — honest
                session.replay_append({   # terminal for the client
                    "done": True, "finish": "failed",
                    "error": f"{type(e).__name__}: {e}",
                    "tokens": list(session.emitted),
                    "sid": session.sid, "step": session.step})
            finally:
                session.replay_finish()

        threading.Thread(target=drive,
                         name=f"recover-{session.sid}",
                         daemon=True).start()
        return session

    def attach_stream(self, sid: str, resume_from: int = 0):
        """Reconnect a client to a recovered (or replay-retained
        terminal) stream by `X-Session-Id`: yields the continuation
        from token index `resume_from` exactly-once.  Raises
        `UnknownSession` (HTTP 410) for an unjournaled/evicted sid,
        ValueError (400) for a live never-crashed stream — its
        original connection still owns it."""
        session = self.sessions.get(sid)
        if session is None:
            raise UnknownSession(
                f"unknown or expired session {sid!r}")
        if not session.attachable:
            raise ValueError(
                f"session {sid!r} is live on its original "
                f"connection and cannot be attached")
        self.sessions.stats.count("attached")
        return session.attach(resume_from=int(resume_from))

    # -- membership reads ---------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def handle_for(self, name: str):
        return self._members[name].handle

    def members(self) -> List[Dict[str, Any]]:
        """Point-in-time per-engine view (stats/rollout surface)."""
        with self._lock:
            return [{
                "name": n, "healthy": m.healthy,
                "quarantined": m.quarantined, "strikes": m.strikes,
                "step": m.step, "family": m.family,
                "in_flight": m.in_flight,
                "queue_depth": m.queue_depth,
                "dispatched": m.dispatched, "failed": m.failed,
                "quarantines": m.quarantines, "draining": m.draining,
            } for n, m in self._members.items()]

    def healthy_names(self) -> List[str]:
        with self._lock:
            return [n for n, m in self._members.items()
                    if m.healthy and not m.quarantined
                    and not m.draining]

    def engine_step(self, name: str) -> int:
        with self._lock:
            m = self._members.get(name)
            return m.step if m is not None else -1

    def engine_family(self, name: str) -> str:
        with self._lock:
            m = self._members.get(name)
            return m.family if m is not None else "default"

    def families(self) -> List[str]:
        """Every checkpoint family any member advertises (including
        unhealthy ones: a family mid-quarantine is still SERVED — a
        request for it sheds honestly rather than 404ing)."""
        with self._lock:
            return sorted({m.family for m in self._members.values()})

    # -- runtime membership (autoscaler surface) ----------------------------
    def add_engine(self, handle) -> None:
        """Admit a new worker at runtime.  The caller must hand over a
        STARTED, warmed handle — the first probe below is a verdict,
        not a warmup, and an unhealthy join simply stays out of
        dispatch until it probes ok."""
        with self._lock:
            if handle.name in self._members:
                raise ValueError(
                    f"duplicate engine name: {handle.name!r}")
            self._members[handle.name] = _Member(handle=handle)
        self._probe_one(handle.name)   # first verdict before traffic
        self.stats.count("joins")
        self.log(f"fleet: engine {handle.name} joined "
                 f"(step {self.engine_step(handle.name)})")
        obs.emit_event("fleet.join", engine=handle.name,
                       step=self.engine_step(handle.name))

    def remove_engine(self, name: str, drain: bool = True,
                      timeout_s: float = 30.0) -> bool:
        """Retire a worker.  `drain=True` stops admissions immediately
        (the member is excluded from `_pick` under the same lock that
        admits) and waits for in-flight work — including held stream
        slots — to finish before dropping the member; returns whether
        the drain completed inside `timeout_s`.  Retirement is
        deliberate, so the member record (strikes, quarantine history)
        leaves with it — a re-added engine starts clean."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                return True            # already gone
            m.draining = True          # no new picks from here on
        drained = True
        if drain:
            deadline = time.monotonic() + float(timeout_s)
            while True:
                with self._lock:
                    mm = self._members.get(name)
                    busy = mm is not None and mm.in_flight > 0
                if not busy:
                    break
                if time.monotonic() >= deadline:
                    drained = False
                    break
                time.sleep(0.005)
        if not drained:
            # the engine is leaving whether its streams finished or
            # not: fail every live session over to a sibling so
            # scale-down never truncates a journaled stream
            kicked = self.sessions.kick_engine(name, "drain timeout")
            if kicked:
                self.log(f"fleet: drain of {name} timed out with "
                         f"{kicked} live stream(s); failing them over")
        with self._lock:
            self._members.pop(name, None)
        self.stats.count("retires")
        self.log(f"fleet: engine {name} retired "
                 f"({'drained' if drained else 'drain timed out'})")
        obs.emit_event("fleet.retire", engine=name, drained=drained)
        return drained

    # -- probing ------------------------------------------------------------
    def _probe_loop(self) -> None:
        period = float(self.spec.probe_period_s)
        while not self._probe_stop.wait(period):
            self.probe_all()

    def probe_all(self) -> None:
        """One probe round over every member (also callable directly —
        tests and the rollout controller tighten timing with it)."""
        for name in self.names():
            self._probe_one(name)

    def _probe_one(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
        if m is None:
            return                    # retired while we iterated
        now = time.monotonic()
        if m.quarantined and now < m.bench_until:
            return                    # still benched; don't even probe
        try:
            with obs.span("router.probe", engine=name):
                h = m.handle.probe()
        except Exception as e:  # noqa: BLE001 — probe failure = strike
            self._strike(name, f"probe failed: {e}")
            return
        with self._lock:
            was_quarantined = m.quarantined
            m.last_health = h
            m.healthy = bool(h.get("ok"))
            m.step = int(h.get("step", -1))
            m.family = str(h.get("family", "default"))
            m.queue_depth = int(h.get("queue_depth", 0))
            if m.healthy:
                m.strikes = 0
                if was_quarantined:
                    m.quarantined = False
                    self.stats.count("readmissions")
        if m.healthy and was_quarantined:
            self.log(f"fleet: engine {name} readmitted after "
                     f"quarantine (probe ok, step {m.step})")
            obs.emit_event("fleet.readmit", engine=name, step=m.step)

    def _strike(self, name: str, why: str) -> None:
        """One probe/dispatch failure; `quarantine_after` consecutive
        strikes bench the engine for a Backoff delay that escalates
        with each consecutive quarantine (the ReplicaSet
        poisoned-round policy, serving-side).  A member retired
        mid-failure is not charged — its record is already gone."""
        with self._lock:
            m = self._members.get(name)
        if m is None:
            return
        with self._lock:
            m.strikes += 1
            m.healthy = False
            if m.strikes < self.spec.quarantine_after or m.quarantined:
                if m.quarantined:
                    # failed its readmission probe: bench it again,
                    # longer (the strike streak keeps growing)
                    m.quarantines += 1
                    m.bench_until = time.monotonic() + \
                        self._backoff.delay(m.quarantines - 1)
                return
            m.quarantined = True
            m.quarantines += 1
            delay = self._backoff.delay(m.quarantines - 1)
            m.bench_until = time.monotonic() + delay
            self.stats.count("quarantines")
        self.log(f"fleet: engine {name} quarantined for "
                 f"{delay:.2f}s ({why})")
        obs.emit_event("fleet.quarantine", engine=name, why=why,
                       bench_s=round(delay, 4))

    # -- dispatch -----------------------------------------------------------
    def _pick(self, exclude: set,
              family: Optional[str] = None) -> Optional[str]:
        """Least-loaded healthy engine (in-flight + probed queue
        depth), excluding already-tried ones; `family` restricts to
        members advertising that checkpoint family (model-aware
        dispatch — None routes anywhere, the legacy single-family
        shape)."""
        with self._lock:
            cands = [(m.in_flight + m.queue_depth, n)
                     for n, m in self._members.items()
                     if n not in exclude and m.healthy
                     and not m.quarantined and not m.draining
                     and (family is None or m.family == family)]
            if not cands:
                return None
            _, name = min(cands)
            self._members[name].in_flight += 1
            return name

    def _release(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.in_flight -= 1

    def _check_family(self, model: Optional[str]) -> Optional[str]:
        """Normalize the requested model family against what the fleet
        SERVES (any member, healthy or not: a family mid-quarantine
        sheds honestly later rather than 404ing).  None/blank routes
        anywhere — the legacy single-family shape.  An unserved family
        raises `UnknownModel` before any engine is picked: a fast 404,
        never a strike, never a Retry-After."""
        if model is None:
            return None
        family = str(model).strip().lower()
        if not family:
            return None
        with self._lock:
            served = {m.family for m in self._members.values()}
        if family not in served:
            self.stats.count("unknown_model")
            obs.emit_event("serve.unknown_model", family=family,
                           served=sorted(served))
            raise UnknownModel(
                f"no engine serves model family {family!r} "
                f"(served: {sorted(served)})")
        return family

    # -- hedging / brownout control signals ---------------------------------
    def _hedge_delay(self) -> float:
        """When to launch the hedge: the windowed p95 latency ("Tail
        at Scale" — hedge only the slowest ~5%), clamped to
        [hedge_min_s, hedge_max_s]; hedge_max_s while there is no
        latency history yet.  Cached ~0.5s."""
        now = time.monotonic()
        if now - self._hedge_cache_t < 0.5:
            return self._hedge_cache
        p95 = self.stats.windowed()["p95_latency_ms"]
        d = (float(self.spec.hedge_max_s) if p95 is None
             else p95 / 1e3)
        d = min(max(d, float(self.spec.hedge_min_s)),
                float(self.spec.hedge_max_s))
        self._hedge_cache, self._hedge_cache_t = d, now
        return d

    def _brownout_sheds(self, priority: str,
                        tenant: str = "default") -> bool:
        """Router-level brownout: when the recent CAPACITY-shed rate
        (sheds where nothing could take the request — brownout's own
        sheds excluded, see RouterStats.observe_shed) crosses
        `brownout_shed_rate`, stop admitting best_effort; at 3x the
        threshold, batch too.  Interactive always passes.  The
        pressure is the TENANT'S OWN capacity-shed rate: one tenant's
        overflow browning out its own background classes is the system
        working — it must never brown out a quiet neighbor's."""
        if priority == "interactive" or \
                float(self.spec.brownout_shed_rate) <= 0:
            return False
        now = time.monotonic()
        if now - self._pressure_t > 0.5:
            win = self.stats.windowed(5.0)
            self._pressure = float(win["capacity_shed_rate"])
            self._pressure_by_tenant = dict(
                win.get("capacity_shed_rate_by_tenant") or {})
            self._pressure_t = now
        pressure = float(self._pressure_by_tenant.get(tenant, 0.0))
        thr = float(self.spec.brownout_shed_rate)
        if priority == "best_effort":
            return pressure >= thr
        return pressure >= 3 * thr

    def _call_handle(self, name: str, mode: str, tokens,
                     timeout, deadline, priority,
                     cancel_event, trace=None,
                     tenant: str = "default") -> Dict[str, Any]:
        """One engine call, forwarding only the QoS keywords the
        handle's `request` signature accepts (duck-typed handles
        predate deadlines/priorities/trace context/tenancy)."""
        with self._lock:
            m = self._members.get(name)
        if m is None:
            raise EngineUnavailable(f"engine {name} retired "
                                    f"mid-dispatch")
        return _handle_call(
            m.handle.request, (mode, tokens),
            {"timeout": timeout, "deadline": deadline,
             "priority": priority, "cancel_event": cancel_event,
             "trace": trace, "tenant": tenant})

    def _try_hedge(self, exclude: set, cancels: Dict[str, Any],
                   launch, deadline, tenant: str = "default",
                   family: Optional[str] = None) -> Optional[str]:
        """Launch the hedged attempt if the budget, the fleet, and the
        deadline allow.  A `serve.hedge` fault abandons the hedge only
        — the primary is untouched.  Returns the hedge engine's name,
        or None (with the spent token refunded when no dispatch
        happened).  The hedge charges the REQUESTING tenant's budget
        and stays inside the request's checkpoint family."""
        rem = qos.remaining_s(deadline)
        if rem is not None and rem <= 0:
            return None               # a hedge would be dead on arrival
        budget = self.tenancy.budget(tenant)
        if not budget.spend():
            self.stats.count("budget_denied")
            return None               # degrade to single-shot, not shed
        name = self._pick(exclude, family=family)
        if name is None:
            budget.refund()
            return None
        try:
            faults.maybe_fault("serve.hedge")
        except faults.FaultError as e:
            self._release(name)
            budget.refund()
            obs.emit_event("serve.hedge_abandoned", engine=name,
                           why=str(e))
            return None
        self.stats.count("hedges")
        cancels[name] = threading.Event()
        launch(name, None)
        return name

    def _hedged_request(self, name: str, mode: str, tokens,
                        timeout, deadline, priority,
                        corr: Optional[str] = None, link=None,
                        info: Optional[dict] = None,
                        tenant: str = "default",
                        family: Optional[str] = None) -> tuple:
        """Dispatch to `name`, hedging onto a sibling once the
        p95-derived delay elapses without a result; first result wins
        and the loser is cancelled.  Owns releasing every in-flight
        slot it holds (the caller's `_pick` took `name`'s).  Returns
        (winner, out) or raises the decisive exception — the
        primary's, unless only the hedge answered.  `corr`/`link`
        tag every leg with the ORIGINATING request's ids (hedge run()
        threads have no thread-local parent — without the explicit
        anchor each leg minted a fresh chain and the hedge was
        invisible in any trace); `info` (when given) reports
        `hedged=True` back to the caller."""
        resq: "queue.Queue" = queue.Queue()
        cancels: Dict[str, threading.Event] = {name: threading.Event()}

        def run(engine_name: str, site: Optional[str]) -> None:
            self.stats.count("attempts")
            try:
                with obs.span("router.attempt", corr=corr,
                              trace=link[0] if link else None,
                              parent=link[1] if link else None,
                              engine=engine_name,
                              hedge=engine_name != name) as asp:
                    if site is not None:
                        faults.maybe_fault(site)
                    out = self._call_handle(
                        name=engine_name, mode=mode, tokens=tokens,
                        timeout=timeout, deadline=deadline,
                        priority=priority,
                        cancel_event=cancels[engine_name],
                        trace=((asp.trace, asp.span_id)
                               if asp.trace else None),
                        tenant=tenant)
                resq.put((engine_name, "ok", out))
            except (Overloaded, DeadlineExpired, TimeoutError,
                    ValueError, Cancelled) as e:
                resq.put((engine_name, "err", e))
            except BaseException as e:  # noqa: BLE001 — engine failure
                with self._lock:
                    mm = self._members.get(engine_name)
                    if mm is not None:
                        mm.failed += 1
                self._strike(engine_name, f"dispatch failed: {e}")
                resq.put((engine_name, "err", e))
            finally:
                self._release(engine_name)

        def launch(engine_name: str, site: Optional[str]) -> None:
            threading.Thread(
                target=run, args=(engine_name, site),
                name=f"route-{engine_name}", daemon=True).start()

        if self.spec.hedge != "on" or len(self._members) <= 1:
            # inline fast path: same code, no thread, no hedge
            run(name, "fleet.dispatch")
            ename, kind, payload = resq.get_nowait()
            if kind == "err":
                raise payload
            return ename, payload

        launch(name, "fleet.dispatch")
        pending = {name}
        hedge_name: Optional[str] = None
        tried_hedge = False
        excs: Dict[str, BaseException] = {}
        winner, out = None, None
        while pending:
            tmo = None if tried_hedge else self._hedge_delay()
            try:
                ename, kind, payload = resq.get(timeout=tmo)
            except queue.Empty:
                tried_hedge = True
                hedge_name = self._try_hedge(
                    set(cancels), cancels, launch, deadline,
                    tenant=tenant, family=family)
                if hedge_name is not None:
                    pending.add(hedge_name)
                    if info is not None:
                        info["hedged"] = True
                continue
            pending.discard(ename)
            if kind == "ok":
                winner, out = ename, payload
                break
            if not isinstance(payload, Cancelled):
                excs[ename] = payload
        if winner is not None:
            for n, ev in cancels.items():
                if n != winner:
                    ev.set()
            if winner == hedge_name:
                self.stats.count("hedge_wins")
            return winner, out
        # every launched attempt failed: the PRIMARY's outcome decides
        # the retry story (the hedge was opportunistic)
        exc = excs.get(name)
        if exc is None and excs:
            exc = next(iter(excs.values()))
        raise exc if exc is not None else EngineUnavailable(
            f"engine {name} vanished mid-dispatch")

    def route(self, mode: str, tokens,
              timeout: Optional[float] = None,
              deadline: Optional[float] = None,
              priority: str = "interactive",
              tenant: Optional[str] = None,
              model: Optional[str] = None) -> Dict[str, Any]:
        """Dispatch one request; retries engine failures on other
        engines (every retry and hedge charging the REQUESTING
        tenant's view of the retry budget, and never outliving
        `deadline`) and sheds (`Overloaded` + per-(tenant, class)
        Retry-After) only when no engine can take it.  `model`
        restricts dispatch to engines advertising that checkpoint
        family — an unserved family raises `UnknownModel` (the honest
        fast 404) before any engine is picked.  The result carries
        `engine`, the member that served it."""
        self._check_lame_duck()
        priority = qos.check_priority(priority)
        tenant = self.tenancy.label(tenant)
        family = self._check_family(model)
        if timeout is None:
            timeout = self.spec.request_timeout_s
        deadline = qos.resolve_deadline(timeout, deadline,
                                        self.spec.request_timeout_s)
        t0 = time.monotonic()
        rem = qos.remaining_s(deadline)
        if rem is not None and rem <= 0:
            # dead on arrival at the router: no engine ever sees it
            self.stats.count("expired_on_arrival")
            raise DeadlineExpired(
                f"dead on arrival at router: deadline passed "
                f"{-rem:.3f}s ago")
        if self._brownout_sheds(priority, tenant):
            self._shed(f"brownout sheds {priority}",
                       priority=priority, brownout=True,
                       tenant=tenant)
        self.stats.observe_routed(tenant)
        tbudget = self.tenancy.budget(tenant)
        tbudget.earn()                # the primary dispatch's earning
        budget = (self.spec.max_attempts
                  if self.spec.max_attempts > 0 else len(self._members))
        tried: set = set()
        saturated = 0
        budget_stopped = False
        last_exc: Optional[BaseException] = None
        # the request's root ids on the router side: inherit the
        # caller's corr (the fleet frontend's span) when dispatched
        # under one, else mint fleet-N — every downstream leg
        # (attempt, hedge, worker, resume) is tagged with them
        corr = obs.current_corr() or f"fleet-{next(self._corr_ids)}"
        hedged: Dict[str, Any] = {}
        with obs.span("router.dispatch", corr=corr, mode=mode,
                      priority=priority, tenant=tenant) as sp:
            link = (sp.trace, sp.span_id) if sp.trace else None
            t1 = time.monotonic()    # admission done; dispatch begins
            for attempt in range(budget):
                rem = qos.remaining_s(deadline)
                if rem is not None and rem <= 0:
                    # a retry must never outlive the client deadline
                    self.stats.count("deadline_terminal")
                    raise DeadlineExpired(
                        f"deadline exhausted after {attempt} "
                        f"attempt(s)")
                if attempt > 0 and not tbudget.spend():
                    self.stats.count("budget_denied")
                    budget_stopped = True
                    break             # single-shot: first outcome stands
                name = self._pick(tried, family=family)
                if name is None:
                    if attempt > 0:
                        tbudget.refund()
                    break
                tried.add(name)
                try:
                    winner, out = self._hedged_request(
                        name, mode, tokens, timeout, deadline,
                        priority, corr=corr, link=link, info=hedged,
                        tenant=tenant, family=family)
                except Overloaded as e:
                    # load, not failure: no strike, try a sibling
                    saturated += 1
                    last_exc = e
                    self.stats.count("retried")
                    continue
                except (DeadlineExpired, TimeoutError):
                    # the request's own deadline died inside the
                    # engine — not an engine failure, no strike, and
                    # retrying elsewhere would only blow it further
                    self.stats.count("deadline_terminal")
                    raise
                except ValueError:
                    self.stats.count("failed")
                    raise          # unservable request, not a failure
                except Exception as e:  # noqa: BLE001 — engine failure
                    # (strike already charged inside _hedged_request)
                    last_exc = e
                    self.stats.count("retried")
                    continue
                with self._lock:
                    m = self._members.get(winner)
                    if m is not None:
                        m.dispatched += 1
                self._shed_backoffs.reset(priority, tenant=tenant)
                self.stats.count("completed")
                t2 = time.monotonic()
                lat = t2 - t0
                self.stats.observe_latency(lat, priority,
                                           tenant=tenant)
                # stage partition shares the e2e clock and its
                # boundary stamps: admit + dispatch == latency exactly
                self.stats.observe_stage("admit", t1 - t0)
                self.stats.observe_stage("dispatch", t2 - t1)
                out["engine"] = winner
                sp.set(engine=winner, attempts=attempt + 1)
                self.requests.record(
                    corr=corr, trace=sp.trace or None, mode=mode,
                    engine=winner, priority=priority, tenant=tenant,
                    outcome="ok",
                    latency_ms=round(lat * 1e3, 3),
                    hedged=bool(hedged), attempts=attempt + 1,
                    stages_ms={
                        "admit": round((t1 - t0) * 1e3, 3),
                        "dispatch": round((t2 - t1) * 1e3, 3)})
                if sp.trace:
                    o = obs.active()
                    p95 = (self.stats.latency_quantile(0.95)
                           if o is not None
                           and o.spec.sample == "tail" else None)
                    obs.sample_trace(sp.trace, lat, p95_s=p95,
                                     hedged=bool(hedged))
                return out
            if budget_stopped and last_exc is not None:
                # the retry budget ran dry: degrade to single-shot —
                # the first attempt's outcome stands, the request is
                # never shed BECAUSE of the budget
                if isinstance(last_exc, Overloaded):
                    self.stats.observe_shed(priority, tenant=tenant)
                    raise last_exc    # the engine's honest Retry-After
                self.stats.count("failed")
                raise EngineUnavailable(
                    f"dispatch failed, retry budget exhausted "
                    f"({len(tried)} engine(s) tried): {last_exc}"
                ) from last_exc
            # nothing left to try: the fleet is saturated or down
            why = ("fleet saturated" if saturated
                   else "no healthy engine available"
                   if not tried else
                   f"all {len(tried)} reachable engine(s) failed")
            self._shed(why, priority=priority, tenant=tenant)

    def _call_stream(self, name: str, tokens, timeout, max_new,
                     deadline, priority, cancel_event,
                     resume_from: int = 0, trace=None,
                     tenant: str = "default"):
        with self._lock:
            m = self._members.get(name)
        if m is None:
            raise EngineUnavailable(f"engine {name} retired "
                                    f"mid-dispatch")
        return _handle_call(
            m.handle.request_stream, (tokens,),
            {"timeout": timeout, "max_new": max_new,
             "deadline": deadline, "priority": priority,
             "cancel_event": cancel_event,
             "resume_from": resume_from, "trace": trace,
             "tenant": tenant})

    def _hedged_stream(self, name: str, tokens, timeout, max_new,
                       deadline, priority,
                       corr: Optional[str] = None, link=None,
                       info: Optional[dict] = None,
                       tenant: str = "default",
                       family: Optional[str] = None) -> tuple:
        """Streaming twin of `_hedged_request`: FIRST BYTE wins — each
        attempt admits its stream and pulls one event; whichever
        event lands first commits that engine, the loser's
        cancel_event tears its slot down mid-decode.  Returns
        (winner, first_event, generator, cancel_event) with the
        winner's in-flight slot STILL HELD (released by the session
        stream wrapper); the cancel_event is the failover path's
        lever for tearing down a stalled winner."""
        resq: "queue.Queue" = queue.Queue()
        sel = threading.Lock()
        state = {"done": False}
        cancels: Dict[str, threading.Event] = {name: threading.Event()}

        def run(engine_name: str, site: Optional[str]) -> None:
            self.stats.count("attempts")
            ev = cancels[engine_name]
            try:
                # the attempt span covers admission through the
                # first-byte commit, anchored under the stream's root
                # (run() threads have no thread-local parent); the
                # worker anchors under THIS span via the trace kwarg
                with obs.span("router.attempt", corr=corr,
                              trace=link[0] if link else None,
                              parent=link[1] if link else None,
                              engine=engine_name,
                              hedge=engine_name != name,
                              stream=True) as asp:
                    if site is not None:
                        faults.maybe_fault(site)
                    gen = self._call_stream(
                        engine_name, tokens, timeout, max_new,
                        deadline, priority, ev,
                        trace=((asp.trace, asp.span_id)
                               if asp.trace else None),
                        tenant=tenant)
                    first = next(gen)  # the first-byte commit
            except (Overloaded, DeadlineExpired, TimeoutError,
                    ValueError, Cancelled, StopIteration) as e:
                self._release(engine_name)
                resq.put((engine_name, "err", e))
                return
            except BaseException as e:  # noqa: BLE001 — engine failure
                self._release(engine_name)
                with self._lock:
                    mm = self._members.get(engine_name)
                    if mm is not None:
                        mm.failed += 1
                self._strike(engine_name,
                             f"stream dispatch failed: {e}")
                resq.put((engine_name, "err", e))
                return
            with sel:
                late = state["done"]
                if not late:
                    # success keeps its in-flight slot held for
                    # _wrap_stream — no release here
                    resq.put((engine_name, "ok", (first, gen)))
            if late:                   # a winner was already chosen
                gen.close()
                self._release(engine_name)

        def launch(engine_name: str, site: Optional[str]) -> None:
            threading.Thread(
                target=run, args=(engine_name, site),
                name=f"stream-{engine_name}", daemon=True).start()

        if self.spec.hedge != "on" or len(self._members) <= 1:
            run(name, "fleet.dispatch")
            ename, kind, payload = resq.get_nowait()
            if kind == "err":
                raise payload
            return ename, payload[0], payload[1], cancels[ename]

        launch(name, "fleet.dispatch")
        pending = {name}
        hedge_name: Optional[str] = None
        tried_hedge = False
        excs: Dict[str, BaseException] = {}
        winner = first = gen = None
        while pending:
            tmo = None if tried_hedge else self._hedge_delay()
            try:
                ename, kind, payload = resq.get(timeout=tmo)
            except queue.Empty:
                tried_hedge = True
                hedge_name = self._try_hedge(
                    set(cancels), cancels, launch, deadline,
                    tenant=tenant, family=family)
                if hedge_name is not None:
                    pending.add(hedge_name)
                    if info is not None:
                        info["hedged"] = True
                continue
            pending.discard(ename)
            if kind == "ok":
                winner, (first, gen) = ename, payload
                break
            if not isinstance(payload, Cancelled):
                excs[ename] = payload
        with sel:
            state["done"] = True
        # any "ok" result in the queue now is a loser that beat the
        # state flag: close it and give back its slot
        while True:
            try:
                ename, kind, payload = resq.get_nowait()
            except queue.Empty:
                break
            if kind == "ok":
                payload[1].close()
                self._release(ename)
        if winner is not None:
            for n, ev in cancels.items():
                if n != winner:
                    ev.set()
            if winner == hedge_name:
                self.stats.count("hedge_wins")
            return winner, first, gen, cancels[winner]
        exc = excs.get(name)
        if exc is None and excs:
            exc = next(iter(excs.values()))
        raise exc if exc is not None else EngineUnavailable(
            f"engine {name} vanished mid-dispatch")

    def route_stream(self, tokens, timeout: Optional[float] = None,
                     max_new: Optional[int] = None,
                     deadline: Optional[float] = None,
                     priority: str = "interactive",
                     tenant: Optional[str] = None,
                     model: Optional[str] = None):
        """Streaming dispatch: pick an engine exactly like `route`,
        but return its token-event iterator instead of a buffered
        result.  Retry-on-other-engine applies ONLY until the first
        byte (a hedge's losing stream is cancelled, never replayed) —
        after that a failure surfaces to the caller, because tokens
        may already be on the wire and a replay would duplicate them.
        The engine's in-flight slot is held until the consumer
        exhausts (or abandons) the stream."""
        self._check_lame_duck()
        priority = qos.check_priority(priority)
        tenant = self.tenancy.label(tenant)
        family = self._check_family(model)
        if timeout is None:
            timeout = self.spec.request_timeout_s
        deadline = qos.resolve_deadline(timeout, deadline,
                                        self.spec.request_timeout_s)
        t0 = time.monotonic()
        # stage-boundary stamps on the tracer's clock (perf_counter):
        # post-hoc stream-stage spans are recorded from these
        p0 = time.perf_counter()
        rem = qos.remaining_s(deadline)
        if rem is not None and rem <= 0:
            self.stats.count("expired_on_arrival")
            raise DeadlineExpired(
                f"dead on arrival at router: deadline passed "
                f"{-rem:.3f}s ago")
        if self._brownout_sheds(priority, tenant):
            self._shed(f"brownout sheds {priority}",
                       priority=priority, brownout=True,
                       tenant=tenant)
        self.stats.observe_routed(tenant)
        tbudget = self.tenancy.budget(tenant)
        tbudget.earn()
        budget = (self.spec.max_attempts
                  if self.spec.max_attempts > 0 else len(self._members))
        tried: set = set()
        saturated = 0
        budget_stopped = False
        last_exc: Optional[BaseException] = None
        corr = obs.current_corr() or f"fleet-{next(self._corr_ids)}"
        hedged: Dict[str, Any] = {}
        # the stream's root span covers ONLY admission through the
        # first-byte commit and closes before the generator is handed
        # out — a span must never stay open across generator yields
        # (the consumer's pull cadence is not ours).  Post-admission
        # stages are recorded post-hoc against `link` at terminal.
        with obs.span("router.stream", corr=corr, mode="generate",
                      priority=priority, tenant=tenant) as sp:
            link = (sp.trace, sp.span_id) if sp.trace else None
            pa = time.perf_counter()  # admission done; dispatch begins
            for attempt in range(budget):
                rem = qos.remaining_s(deadline)
                if rem is not None and rem <= 0:
                    self.stats.count("deadline_terminal")
                    raise DeadlineExpired(
                        f"deadline exhausted after {attempt} "
                        f"attempt(s)")
                if attempt > 0 and not tbudget.spend():
                    self.stats.count("budget_denied")
                    budget_stopped = True
                    break
                name = self._pick(tried, family=family)
                if name is None:
                    if attempt > 0:
                        tbudget.refund()
                    break
                tried.add(name)
                try:
                    winner, first, gen, cancel = self._hedged_stream(
                        name, tokens, timeout, max_new, deadline,
                        priority, corr=corr, link=link, info=hedged,
                        tenant=tenant, family=family)
                except Overloaded as e:
                    saturated += 1
                    last_exc = e
                    self.stats.count("retried")
                    continue
                except (DeadlineExpired, TimeoutError):
                    self.stats.count("deadline_terminal")
                    raise
                except ValueError:
                    self.stats.count("failed")
                    raise
                except Exception as e:  # noqa: BLE001 — engine failure
                    last_exc = e
                    self.stats.count("retried")
                    continue
                # committed to this engine: open the durable session —
                # the journal + leg pump that let the stream survive
                # the engine (docs/SERVING.md, "Mid-stream failover").
                # It carries the originating corr + trace link so a
                # failover leg admitted later lands in the SAME trace.
                session = self.sessions.open(
                    prompt=tokens, max_new=max_new, deadline=deadline,
                    priority=priority, engine=winner,
                    step=self.engine_step(winner), corr=corr,
                    trace=link, tenant=tenant,
                    family=self.engine_family(winner))
                leg = _StreamLeg(self, session, winner, gen, cancel,
                                 first=first)
                sp.set(engine=winner, attempts=attempt + 1)
                return self._session_stream(
                    session, leg, t0, priority, timeout,
                    p0=p0, pa=pa, p1=time.perf_counter(),
                    link=link, hedged=bool(hedged))
        if budget_stopped and last_exc is not None:
            if isinstance(last_exc, Overloaded):
                self.stats.observe_shed(priority, tenant=tenant)
                raise last_exc
            self.stats.count("failed")
            raise EngineUnavailable(
                f"stream dispatch failed, retry budget exhausted "
                f"({len(tried)} engine(s) tried): {last_exc}"
            ) from last_exc
        why = ("fleet saturated" if saturated
               else "no healthy engine available"
               if not tried else
               f"all {len(tried)} reachable engine(s) failed")
        self._shed(why, priority=priority, tenant=tenant)

    def _session_stream(self, session, leg, t0: float, priority: str,
                        timeout: Optional[float], p0=None, pa=None,
                        p1=None, link=None, hedged: bool = False,
                        initial_err=None):
        """Consumer loop of a durable stream: journals every token by
        absolute sequence number, dedupes the splice (each index
        reaches the client AT MOST once), arms the per-stream idle
        watchdog, and on any leg death — transport break, silent
        stall, sequence gap, drain-timeout kick — swaps in a resume
        leg from `_failover_leg`.  The client iterator only learns a
        leg died when resume itself is impossible.  `p0`/`pa`/`p1`
        are the admit / dispatch-start / first-byte stage stamps from
        route_stream (tracer clock); the terminal records the stream
        stages post-hoc against `link`."""
        sstats = self.sessions.stats
        wal = self.sessions.wal
        idle = float(self.spec.stream_idle_s)
        state = "failed"
        finished = False
        staged = False
        # the durable-session protocol: the FIRST event a client sees
        # carries the sid (X-Session-Id's value) + router epoch, so a
        # reconnect after a crash/handoff can attach to the journal
        sent_first = False

        def _finish(outcome: str) -> None:
            """Terminal bookkeeping, exactly once: post-hoc stream
            stage spans (admit/first_token/decode partition the e2e
            latency exactly — one clock, shared boundary stamps), the
            stage histograms, the /debug/requests record, and the
            tail-sampling verdict for this request's trace."""
            nonlocal staged
            if staged:
                return
            staged = True
            p3 = time.perf_counter()
            lat = (p3 - p0) if p0 is not None else 0.0
            stages: Dict[str, float] = {}
            if p0 is not None and pa is not None and p1 is not None:
                stages = {"admit": pa - p0,
                          "first_token": p1 - pa,
                          "decode": p3 - p1}
                for st, secs in stages.items():
                    self.stats.observe_stage(st, secs)
            o = obs.active()
            if o is not None and link and p1 is not None:
                tr, psid = link
                o.tracer.add_span(
                    "stream.first_token", pa, p1 - pa,
                    corr=session.corr, trace=tr, parent=psid,
                    engine=session.engine)
                o.tracer.add_span(
                    "stream.decode", p1, p3 - p1, corr=session.corr,
                    trace=tr, parent=psid, engine=session.engine,
                    tokens=len(session.emitted),
                    resumes=session.resumes)
            self.requests.record(
                corr=session.corr, trace=link[0] if link else None,
                mode="stream", engine=session.engine,
                priority=priority,
                tenant=getattr(session, "tenant", "default"),
                outcome=outcome,
                latency_ms=round(lat * 1e3, 3), hedged=hedged,
                resumes=session.resumes,
                tokens=len(session.emitted),
                stages_ms={k: round(v * 1e3, 3)
                           for k, v in stages.items()})
            if link:
                p95 = (self.stats.latency_quantile(0.95)
                       if o is not None
                       and o.spec.sample == "tail" else None)
                obs.sample_trace(
                    link[0], lat, p95_s=p95,
                    failed=outcome not in ("done", "spliced"),
                    hedged=hedged, resumed=session.resumes > 0)

        def terminal(ev):
            """Splice the terminal event: the FULL token list from
            the journal (a resumed leg's own `tokens` is only its
            suffix), marked `spliced` when any failover happened."""
            out = dict(ev)
            out["engine"] = session.engine
            out.setdefault("sid", session.sid)
            if self.epoch:
                out.setdefault("epoch", self.epoch)
            if session.emitted or "tokens" in out:
                out["tokens"] = list(session.emitted)
            if session.resumes:
                out["spliced"] = True
                out["resumes"] = session.resumes
                sstats.count("spliced")
                obs.emit_event("stream.spliced", sid=session.sid,
                               engine=session.engine,
                               resumes=session.resumes,
                               tokens=len(session.emitted))
            return out

        try:
            if leg is None:
                # recovery arm: a WAL-recovered stream enters with no
                # live leg — the crash WAS the leg's death, so admit
                # the resume leg through the ordinary failover path
                # (pinned fingerprint, resume_from = journaled-prefix
                # length); None means the journal was already complete
                leg = self._failover_leg(
                    session, None,
                    initial_err or EngineUnavailable(
                        f"recovered stream {session.sid} has no "
                        f"live leg"), timeout)
            while leg is not None:
                try:
                    entry = session.q.get(
                        timeout=idle if idle > 0 else None)
                except queue.Empty:
                    sstats.count("idle_timeouts")
                    leg = self._failover_leg(session, leg, TimeoutError(
                        f"stream idle > {idle:.3f}s on engine "
                        f"{session.engine} (silent stall)"), timeout)
                    if leg is None:
                        break
                    continue
                src, kind, payload = entry
                if src is None:           # drain-timeout kick
                    leg = self._failover_leg(
                        session, leg, EngineUnavailable(
                            f"engine {session.engine} retiring "
                            f"mid-stream: {payload}"), timeout)
                    if leg is None:
                        break
                    continue
                if src is not leg:
                    # a zombie leg woke up after failover: its tokens
                    # are already journaled (or being re-derived by
                    # the resume leg) and its control events describe
                    # a leg we abandoned — drop everything
                    if kind == "ev" and not payload.get("done"):
                        sstats.count("dup_tokens")
                    continue
                if kind in ("err", "end"):
                    err = (payload if kind == "err" else
                           EngineUnavailable(
                               f"engine {session.engine} stream ended "
                               f"without a terminal event"))
                    leg = self._failover_leg(session, leg, err,
                                             timeout)
                    if leg is None:
                        break
                    continue
                ev = payload
                if ev.get("done"):
                    state = "spliced" if session.resumes else "done"
                    finished = True
                    _finish(state)
                    yield terminal(ev)
                    return
                i = int(ev.get("i", session.next_i))
                if i < session.next_i:
                    sstats.count("dup_tokens")
                    continue
                if i > session.next_i:
                    sstats.count("gap_events")
                    leg = self._failover_leg(
                        session, leg, RuntimeError(
                            f"sequence gap on {session.engine}: "
                            f"expected index {session.next_i}, "
                            f"got {i}"), timeout)
                    if leg is None:
                        break
                    continue
                session.record(ev["token"])
                if wal is not None:
                    # write-ahead of delivery: the journal sees the
                    # token before the client does (group-committed
                    # off the critical path by the WAL's flusher)
                    wal.append_tok(session.sid, session.next_i - 1,
                                   int(ev["token"]))
                if not sent_first:
                    ev = dict(ev)
                    ev["sid"] = session.sid
                    if self.epoch:
                        ev["epoch"] = self.epoch
                    sent_first = True
                yield ev
            # _failover_leg returned None: the journal already holds
            # every token (the leg died between its last token and
            # its terminal event) — synthesize the done honestly
            state, finished = "spliced", True
            _finish(state)
            yield terminal({"done": True, "finish": "length",
                            "step": session.step})
        except _FailoverStale as e:
            # no same-fingerprint engine remains: an honest terminal
            # with the journaled prefix, never a cross-checkpoint lie
            state, finished = "failover_stale", True
            _finish(state)
            yield {"done": True, "finish": "failover_stale",
                   "engine": session.engine, "step": session.step,
                   "sid": session.sid,
                   "tokens": list(session.emitted),
                   "resumes": session.resumes, "error": str(e)}
        finally:
            _finish(state if finished else "failed")
            if leg is not None:
                (leg.release if finished else leg.abandon)()
            self.sessions.close(session, state)
            if finished:
                with self._lock:
                    m = self._members.get(session.engine)
                    if m is not None:
                        m.dispatched += 1
                tenant = getattr(session, "tenant", "default")
                self._shed_backoffs.reset(priority, tenant=tenant)
                self.stats.count("completed")
                self.stats.observe_latency(time.monotonic() - t0,
                                           priority, tenant=tenant)
            else:
                self.stats.count("failed")

    def _failover_leg(self, session, old_leg, err, timeout):
        """Replace a dead stream leg: re-admit (prompt ‖ emitted
        prefix) as fresh prefill on a sibling pinned to the SAME
        checkpoint fingerprint, continuing from the next owed index —
        sound because greedy decode is bit-deterministic given
        (fingerprint, prompt, tokens-so-far).  Raises `_FailoverStale`
        when only other fingerprints remain, and otherwise degrades
        to `err` — the pre-failover terminal error — whenever resume
        is off, denied (budget/deadline), faulted (`serve.resume`),
        or inadmissible: failover can never turn a crash into a hang
        or a duplicate.  Returns the new leg, or None when the
        journal is already complete."""
        sstats = self.sessions.stats
        old_engine = session.engine
        if old_leg is not None:
            old_leg.abandon()
        sstats.count("failovers")
        session.resumes += 1
        session.state = "failed_over"
        with self._lock:
            m = self._members.get(old_engine)
            draining = m is None or m.draining
        if old_leg is not None and not draining:
            # a deliberate retirement is not the engine's fault; a
            # mid-stream death is.  Recovery (old_leg None) never
            # strikes: the ROUTER died, not the engine — and the
            # journaled engine is a fine resume candidate.
            self._strike(old_engine, f"stream leg failed: {err}")
        if self.spec.resume != "on":
            raise err
        rem = qos.remaining_s(session.deadline)
        if rem is not None and rem <= 0:
            sstats.count("resume_denied")
            self.stats.count("deadline_terminal")
            raise DeadlineExpired(
                f"stream leg died ({err}) with deadline already "
                f"exhausted") from err
        try:
            # one resume attempt per visit: an injected failure
            # abandons the resume and the stream degrades to the
            # pre-failover terminal error
            faults.maybe_fault("serve.resume")
        except Exception:  # noqa: BLE001 — injected fault
            sstats.count("resume_faults")
            raise err
        if session.max_new is not None and \
                session.next_i >= session.max_new:
            return None               # journal already complete
        # the resume charges the tenant that OWNS the stream: one
        # tenant's straggler storm of failovers drains its own floor
        # and the shared bucket, never a neighbor's floor
        tbudget = self.tenancy.budget(
            getattr(session, "tenant", "default"))
        tried = {old_engine} if old_leg is not None else set()
        while True:
            if not tbudget.spend():
                sstats.count("resume_denied")
                self.stats.count("budget_denied")
                raise err
            name, other_steps = self._pick_resume(
                tried, session.step,
                family=getattr(session, "family", None))
            if name is None:
                tbudget.refund()
                if other_steps:
                    raise _FailoverStale(
                        f"no engine pinned to fingerprint "
                        f"({getattr(session, 'family', 'default')}, "
                        f"{session.step}) remains (siblings serve a "
                        f"different fingerprint); refusing to splice "
                        f"across checkpoints") from err
                sstats.count("resume_denied")
                raise err
            tried.add(name)
            with self._lock:
                mem = self._members.get(name)
            if mem is not None:
                acc = _accepted_kwargs(mem.handle.request_stream)
                if acc is not None and "resume_from" not in acc:
                    # a handle that silently dropped resume_from
                    # would replay the stream from index 0 — degrade
                    # instead of splicing garbage
                    self._release(name)
                    tbudget.refund()
                    sstats.count("resume_denied")
                    raise err
            cancel = threading.Event()
            at = session.next_i
            try:
                self.stats.count("attempts")
                # the resume leg is anchored on the session's stored
                # trace link and tagged with the ORIGINATING corr —
                # this code runs on whatever thread the consumer loop
                # happens to own, seconds after the root span closed,
                # so only the explicit anchor keeps primary and
                # resumed legs in ONE trace (the old leg minted a
                # fresh chain and the splice was invisible)
                with obs.span(
                        "router.resume", corr=session.corr,
                        trace=(session.trace[0]
                               if session.trace else None),
                        parent=(session.trace[1]
                                if session.trace else None),
                        engine=name, from_engine=old_engine,
                        at=at) as rsp:
                    gen = self._call_stream(
                        name, session.resume_tokens(), timeout,
                        session.max_new, session.deadline,
                        session.priority, cancel, resume_from=at,
                        trace=((rsp.trace, rsp.span_id)
                               if rsp.trace else None),
                        tenant=getattr(session, "tenant", "default"))
                    first = next(gen)
            except Overloaded:
                self._release(name)
                continue              # saturated sibling: try another
            except ValueError as e:
                self._release(name)
                sstats.count("resume_denied")
                raise err from e      # inadmissible resume: degrade
            except DeadlineExpired as e:
                self._release(name)
                self.stats.count("deadline_terminal")
                raise e from err
            except StopIteration:
                self._release(name)
                continue
            except BaseException as e:  # noqa: BLE001 — engine died
                self._release(name)
                with self._lock:
                    mm = self._members.get(name)
                    if mm is not None:
                        mm.failed += 1
                self._strike(name, f"resume dispatch failed: {e}")
                continue
            session.engine = name
            sstats.count("resumed")
            if self.sessions.wal is not None:
                self.sessions.wal.append_resume(session.sid, name, at)
            obs.emit_event("stream.resume", sid=session.sid,
                           from_engine=old_engine, engine=name,
                           at=at, resumes=session.resumes,
                           why=str(err))
            self.log(f"fleet: stream {session.sid} resumed on "
                     f"{name} from token {at} ({err})")
            return _StreamLeg(self, session, name, gen, cancel,
                              first=first)

    def _pick_resume(self, exclude: set, step: int,
                     family: Optional[str] = None):
        """Least-loaded healthy engine pinned to the `(family, step)`
        fingerprint (in-flight slot taken), or (None, whether engines
        at OTHER fingerprints exist) — the caller's stale-vs-degrade
        decision.  `family=None` matches on step alone (legacy
        sessions)."""
        with self._lock:
            cands = []
            other_fps = False
            for n, m in self._members.items():
                if (n in exclude or not m.healthy or m.quarantined
                        or m.draining):
                    continue
                if int(m.step) != int(step) or (
                        family is not None and m.family != family):
                    other_fps = True
                    continue
                cands.append((m.in_flight + m.queue_depth, n))
            if not cands:
                return None, other_fps
            _, name = min(cands)
            self._members[name].in_flight += 1
            return name, other_fps

    def _shed(self, why: str, priority: str = "interactive",
              brownout: bool = False,
              tenant: str = "default") -> None:
        self.stats.observe_shed(priority, brownout=brownout,
                                tenant=tenant)
        retry = self._shed_backoffs.shed_delay(priority, tenant=tenant)
        # a shed is a terminal outcome: record it (corr/trace from
        # the enclosing dispatch span, when one is open) and keep its
        # trace — sheds are always interesting to the tail sampler
        tr = obs.trace_context()
        self.requests.record(
            corr=obs.current_corr(), trace=tr[0] if tr else None,
            priority=priority, tenant=tenant, outcome="shed", why=why)
        if tr:
            obs.sample_trace(tr[0], 0.0, shed=True)
        obs.emit_event("serve.shed", why=f"router: {why}",
                       priority=priority, tenant=tenant,
                       retry_after=round(retry, 4))
        raise Overloaded(f"request shed ({why}); retry after "
                         f"{retry:.3f}s", retry_after=retry)

    # -- rollout support ----------------------------------------------------
    def pick_canary(self, family: Optional[str] = None
                    ) -> Optional[str]:
        """The engine to canary a new checkpoint on: healthy and
        carrying the LEAST traffic — a bad fingerprint should touch as
        little of the fleet's load as possible.  `family` scopes the
        choice to one checkpoint family's members (per-family rollout
        canaries)."""
        with self._lock:
            cands = [(m.in_flight + m.queue_depth, n)
                     for n, m in self._members.items()
                     if m.healthy and not m.quarantined
                     and not m.draining
                     and (family is None or m.family == family)]
        return min(cands)[1] if cands else None

    def snapshot(self) -> Dict[str, Any]:
        out = self.stats.snapshot()
        out["engines"] = self.members()
        out["healthy_engines"] = len(self.healthy_names())
        out["streams"] = self.sessions.snapshot()
        out["families"] = self.families()
        out["by_tenant"] = self.stats.tenants.snapshot()
        out["tenancy"] = self.tenancy.snapshot()
        out["epoch"] = self.epoch
        out["lame_duck"] = self.lame_duck is not None
        return out


class _StreamLeg:
    """One engine-side transport attempt of a durable stream: a pump
    thread drains the handle's event iterator into the session's ONE
    queue tagged with this leg's identity, and the leg owns exactly
    one in-flight slot on its engine until `release()` (idempotent).
    `abandon()` is the failover teardown — cancel the engine-side
    decode, close the iterator, give back the slot; the pump may stay
    blocked inside the iterator (a zombie), but its late writes carry
    this leg's tag and the session consumer drops them."""

    def __init__(self, router, session, engine: str, gen, cancel,
                 first=None):
        self.router = router
        self.session = session
        self.engine = engine
        self.gen = gen
        self.cancel = cancel
        self._first = first
        self._released = False
        self._rel_lock = threading.Lock()
        threading.Thread(
            target=self._pump,
            name=f"leg-{session.sid}-{engine}", daemon=True).start()

    def _pump(self) -> None:
        q = self.session.q
        try:
            if self._first is not None:
                q.put((self, "ev", self._first))
            for ev in self.gen:
                q.put((self, "ev", ev))
            q.put((self, "end", None))
        except BaseException as e:  # noqa: BLE001 — leg death = event
            q.put((self, "err", e))

    def release(self) -> None:
        with self._rel_lock:
            if self._released:
                return
            self._released = True
        self.router._release(self.engine)

    def abandon(self) -> None:
        self.cancel.set()
        try:
            self.gen.close()
        except Exception:  # noqa: BLE001 — pump mid-next(): harmless
            pass
        self.release()
