"""Fleet router: health-driven dispatch over N engine workers.

One engine per process caps serving throughput at one chip's tok/s and
makes every crash a 100% outage; the router is the horizontal half of
the north star ("millions of users") and the modern answer to the
reference's ZeroMQ server pool (PAPER.md L4) — survive partial failure
by construction, the TensorFlow-paper argument (arxiv 1605.08695).

Three moving parts:

  * `EngineHandle` — the uniform worker surface.  `LocalEngineHandle`
    wraps an in-process `InferenceServer` (threads: the CPU-test and
    single-machine shape); `HttpEngineHandle` speaks to a separate
    `singa_tpu.main serve --pinned` process over its HTTP surface
    (/healthz, /stats, /generate, /predict, /admin/reload) — the
    subprocess deployment whose membership comes from
    `parallel.bootstrap.parse_hostfile`.
  * `Router` — per-request dispatch to the least-loaded healthy
    engine (in-flight + last-probed queue depth), with
    retry-on-other-engine: an engine failure (connection refused, a
    500, an injected `fleet.dispatch` fault) charges the engine a
    strike and the request moves on; the client sees a failure only
    when every admissible engine has been tried.  `Overloaded` from
    one engine is load, not failure — the request retries elsewhere
    without a strike.  When NO engine can take the request the router
    itself sheds with `Overloaded` + an escalating Backoff
    `Retry-After`, mirroring the MicroBatcher's admission story one
    level up.
  * the probe loop — every `probe_period_s` each member's
    /healthz + ServeStats are read; a degraded verdict pulls the
    engine out of dispatch (it re-enters the moment it reports ok),
    while hard probe failures accumulate strikes toward quarantine.
    Quarantine/readmission mirrors `ReplicaSet`'s poisoned-round
    policy: `quarantine_after` consecutive strikes bench the engine
    for a `utils.faults.Backoff` delay that doubles on each
    consecutive re-quarantine, and a clean probe after the bench
    readmits it (counted, evented — `fleet.quarantine` /
    `fleet.readmit`).

Rollout (canary / promote / rollback) rides on top of this in
`fleet.py`; the router only answers "who is healthy and least loaded
right now" and "move this request somewhere else".
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import obs
from ..utils import faults
from .batcher import DeadlineExpired, Overloaded


class EngineUnavailable(RuntimeError):
    """The chosen engine could not take the request at all (process
    dead, connection refused, handler crashed) — retried on another
    engine and charged to this one as a strike."""


@dataclass(frozen=True)
class RouterSpec:
    """Router config grammar (`--fleet_spec`, the ServeSpec mold):
    comma/semicolon-separated `key=value`."""
    probe_period_s: float = 0.25   # health-probe cadence per engine
    quarantine_after: int = 2      # consecutive strikes -> quarantine
    readmit_base_s: float = 0.25   # Backoff base for the bench time
    readmit_cap_s: float = 10.0    # Backoff cap
    max_attempts: int = 0          # engines tried per request (0 = all)
    request_timeout_s: float = 5.0
    seed: int = 0

    def __post_init__(self):
        if int(self.quarantine_after) < 1:
            raise ValueError(f"quarantine_after must be >= 1, got "
                             f"{self.quarantine_after}")
        if float(self.probe_period_s) <= 0:
            raise ValueError(f"probe_period_s must be > 0, got "
                             f"{self.probe_period_s}")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "RouterSpec":
        kw: Dict[str, Any] = {}
        types = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key, sep, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or key not in types:
                    raise ValueError(f"unknown key {key!r}")
                kw[key] = (float(val) if "float" in str(types[key])
                           else int(val))
            except ValueError as e:
                raise ValueError(f"bad fleet spec entry {part!r} "
                                 f"(want key=value): {e}") from e
        return cls(**kw)


# -- engine handles ---------------------------------------------------------

class LocalEngineHandle:
    """In-process worker: a pinned `InferenceEngine` + `MicroBatcher`
    wrapped in an `InferenceServer` (no HTTP — the router IS the
    frontend).  `kill()`/`revive()` give tests and the bench a
    deterministic crash/recovery lever."""

    def __init__(self, name: str, server):
        self.name = name
        self.server = server          # serve.InferenceServer
        self.engine = server.engine
        self._alive = True

    def start(self) -> None:
        self.server.start()
        self._alive = True

    def stop(self) -> None:
        self._alive = False
        self.server.stop()

    def kill(self) -> None:
        """Simulate a worker crash: requests and probes fail until
        revive()."""
        self._alive = False
        self.server.stop()

    def revive(self) -> None:
        self.server.start()
        self._alive = True

    def probe(self) -> Dict[str, Any]:
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        h = dict(self.engine.health())
        h["queue_depth"] = self.engine.stats.queue_depth
        return h

    def stats_snapshot(self) -> Dict[str, Any]:
        return self.server.snapshot()

    def request(self, mode: str, tokens,
                timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        call = (self.server.generate if mode == "generate"
                else self.server.predict)
        try:
            return call(tokens, timeout=timeout)
        except (Overloaded, DeadlineExpired, TimeoutError, ValueError):
            raise
        except Exception as e:  # noqa: BLE001 — batch failed / stopped
            raise EngineUnavailable(
                f"engine {self.name} failed: {e}") from e

    def request_stream(self, tokens, timeout: Optional[float] = None,
                       max_new: Optional[int] = None):
        """Streaming generate (cb engines only).  Admission happens
        HERE, before any event is yielded — the router's commit point
        for retry-on-other-engine.  Returns an iterator of ndjson-
        shaped dicts: {"token": t} per token, then the final
        {"done": True, ...} summary."""
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        try:
            ticket = self.server.generate_stream(tokens,
                                                 timeout=timeout,
                                                 max_new=max_new)
        except (Overloaded, DeadlineExpired, TimeoutError, ValueError):
            raise
        except Exception as e:  # noqa: BLE001 — no cb / stopped
            raise EngineUnavailable(
                f"engine {self.name} cannot stream: {e}") from e
        budget = (timeout if timeout and timeout > 0
                  else self.engine.spec.request_timeout_s) + 30.0

        def gen():
            for kind, payload in ticket.events(timeout=budget):
                if kind == "tok":
                    yield {"token": payload}
                else:
                    out = dict(payload)
                    out["done"] = True
                    yield out
        return gen()

    def reload(self, step: Optional[int] = None) -> Dict[str, Any]:
        if not self._alive:
            raise EngineUnavailable(f"engine {self.name} is down")
        outcome = self.engine.reload_to(step)
        return {"outcome": outcome, "step": self.engine.params_step}


class HttpEngineHandle:
    """Worker behind a URL: a `singa_tpu.main serve --pinned` process
    (membership from a hostfile).  Maps the server's status codes back
    to the router's exception vocabulary."""

    def __init__(self, name: str, base_url: str,
                 connect_timeout_s: float = 5.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.connect_timeout_s = connect_timeout_s

    def _call(self, method: str, path: str,
              payload: Optional[dict] = None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        data = (json.dumps(payload).encode()
                if payload is not None else None)
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.connect_timeout_s) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read())
            except Exception:  # noqa: BLE001 — non-JSON error body
                pass
            if e.code == 503 and path == "/healthz":
                return body or {"ok": False, "status": "degraded"}
            if e.code == 503:
                raise Overloaded(
                    body.get("error", "overloaded"),
                    retry_after=float(body.get("retry_after", 0.0)))
            if e.code == 504:
                raise DeadlineExpired(body.get("error", "deadline"))
            if e.code == 400:
                raise ValueError(body.get("error", "bad request"))
            raise EngineUnavailable(
                f"engine {self.name}: HTTP {e.code} "
                f"{body.get('error', '')}")
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise EngineUnavailable(
                f"engine {self.name} unreachable: {e}") from e

    def probe(self) -> Dict[str, Any]:
        h = self._call("GET", "/healthz")
        try:
            snap = self._call("GET", "/stats")
            h["queue_depth"] = snap.get("queue_depth", 0)
        except EngineUnavailable:
            h["queue_depth"] = 0
        return h

    def stats_snapshot(self) -> Dict[str, Any]:
        return self._call("GET", "/stats")

    def request(self, mode: str, tokens,
                timeout: Optional[float] = None) -> Dict[str, Any]:
        toks = (tokens.tolist() if isinstance(tokens, np.ndarray)
                else list(tokens))
        payload = {"tokens": [int(t) for t in toks]}
        if timeout is not None:
            payload["timeout"] = timeout
        budget = (timeout or self.connect_timeout_s) + 30.0
        return self._call("POST", f"/{mode}", payload, timeout=budget)

    def request_stream(self, tokens, timeout: Optional[float] = None,
                       max_new: Optional[int] = None):
        """Streaming generate over HTTP: POST {"stream": true} and
        decode the chunked ndjson line-by-line WITHOUT buffering the
        body.  The response status is the commit point: admission
        errors surface as mapped exceptions before any line is
        yielded; after that a transport failure is a mid-stream
        RuntimeError (not retriable — tokens already flowed)."""
        toks = (tokens.tolist() if isinstance(tokens, np.ndarray)
                else list(tokens))
        payload: Dict[str, Any] = {"tokens": [int(t) for t in toks],
                                   "stream": True}
        if timeout is not None:
            payload["timeout"] = timeout
        if max_new is not None:
            payload["max_new"] = int(max_new)
        budget = (timeout or self.connect_timeout_s) + 30.0
        req = urllib.request.Request(
            f"{self.base_url}/generate",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(req, timeout=budget)
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read())
            except Exception:  # noqa: BLE001 — non-JSON error body
                pass
            if e.code == 503:
                raise Overloaded(
                    body.get("error", "overloaded"),
                    retry_after=float(body.get("retry_after", 0.0)))
            if e.code == 504:
                raise DeadlineExpired(body.get("error", "deadline"))
            if e.code == 400:
                raise ValueError(body.get("error", "bad request"))
            raise EngineUnavailable(
                f"engine {self.name}: HTTP {e.code} "
                f"{body.get('error', '')}")
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise EngineUnavailable(
                f"engine {self.name} unreachable: {e}") from e

        def gen():
            try:
                with resp:
                    for line in resp:
                        line = line.strip()
                        if not line:
                            continue
                        ev = json.loads(line)
                        if "error" in ev and "done" not in ev:
                            raise RuntimeError(
                                f"engine {self.name} stream failed: "
                                f"{ev['error']}")
                        yield ev
            except (urllib.error.URLError, ConnectionError,
                    OSError) as e:
                raise RuntimeError(
                    f"engine {self.name} stream broken: {e}") from e
        return gen()

    def reload(self, step: Optional[int] = None) -> Dict[str, Any]:
        return self._call("POST", "/admin/reload", {"step": step},
                          timeout=60.0)


# -- router -----------------------------------------------------------------

@dataclass
class _Member:
    handle: Any
    healthy: bool = True          # last probe verdict (soft: re-enters
    step: int = -1                # on the next ok probe)
    queue_depth: int = 0
    in_flight: int = 0
    strikes: int = 0              # consecutive probe/dispatch failures
    quarantined: bool = False
    quarantines: int = 0          # lifetime count (drives the Backoff)
    bench_until: float = 0.0      # monotonic readmission-probe time
    dispatched: int = 0
    failed: int = 0
    draining: bool = False        # retiring: no new admissions, pops
    last_health: Dict[str, Any] = field(default_factory=dict)  # when drained


class RouterStats:
    """Aggregate router counters (RouterStats ≈ the fleet-level
    ServeStats; per-engine detail lives in Router.members()).

    Beside the lifetime counters, `windowed()` reports rates over the
    last `window_s` seconds — the autoscaler's control inputs.  A
    cumulative shed counter can't distinguish "shed a lot at 9am" from
    "shedding right now"; the windowed view can."""

    FIELDS = ("routed", "completed", "retried", "failed", "shed",
              "quarantines", "readmissions", "joins", "retires")

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._latencies: List[float] = []
        self._t0 = time.monotonic()
        self._routed_t: deque = deque(maxlen=16384)   # arrival stamps
        self._shed_t: deque = deque(maxlen=16384)
        self._done_t: deque = deque(maxlen=16384)     # (stamp, latency)

    def count(self, fieldname: str, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            setattr(self, fieldname, getattr(self, fieldname) + n)
            if fieldname == "routed":
                self._routed_t.extend([now] * n)
            elif fieldname == "shed":
                self._shed_t.extend([now] * n)

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)
            if len(self._latencies) > 4096:
                del self._latencies[:2048]
            self._done_t.append((time.monotonic(), seconds))

    def windowed(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """Rates over the trailing window (capped at uptime so a
        young process isn't diluted toward zero)."""
        now = time.monotonic()
        with self._lock:
            window = float(window_s if window_s is not None
                           else self.window_s)
            window = min(window, max(now - self._t0, 1e-6))
            cut = now - window
            routed = sum(1 for t in self._routed_t if t >= cut)
            shed = sum(1 for t in self._shed_t if t >= cut)
            lats = sorted(l for t, l in self._done_t if t >= cut)

        def q(frac):
            if not lats:
                return None
            return round(
                lats[min(int(frac * len(lats)), len(lats) - 1)] * 1e3, 3)
        return {
            "window_s": round(window, 3),
            "routed": routed,
            "shed": shed,
            "completed": len(lats),
            "qps": round(len(lats) / window, 3),
            "shed_rate": round(shed / max(routed, 1), 4),
            "p50_latency_ms": q(0.5),
            "p95_latency_ms": q(0.95),
        }

    def latency_quantile(self, q: float) -> Optional[float]:
        with self._lock:
            lats = sorted(self._latencies)
        if not lats:
            return None
        return lats[min(int(q * len(lats)), len(lats) - 1)]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
        p50, p95 = (self.latency_quantile(0.5),
                    self.latency_quantile(0.95))
        out["p50_latency_ms"] = (round(p50 * 1e3, 3)
                                 if p50 is not None else None)
        out["p95_latency_ms"] = (round(p95 * 1e3, 3)
                                 if p95 is not None else None)
        win = self.windowed()
        out["qps_recent"] = win["qps"]
        out["shed_rate_recent"] = win["shed_rate"]
        out["p95_latency_recent_ms"] = win["p95_latency_ms"]
        return out

    def register_into(self, registry,
                      prefix: str = "singa_fleet") -> None:
        from ..obs.metrics import Sample

        def collect():
            snap = self.snapshot()
            out = [Sample(f"{prefix}_{k}_total", "counter",
                          f"fleet router counter {k!r}",
                          float(snap[k])) for k in self.FIELDS]
            out += [Sample(f"{prefix}_{k}", "gauge",
                           f"fleet router gauge {k!r}", float(snap[k]))
                    for k in ("p50_latency_ms", "p95_latency_ms",
                              "qps_recent", "shed_rate_recent",
                              "p95_latency_recent_ms")
                    if snap.get(k) is not None]
            return out

        registry.register_collector(collect)


class Router:
    """See module docstring.  Thread-safe: frontend threads call
    `route`, one daemon thread runs `_probe_loop`, and the rollout
    controller reads `members()` / calls `handle_for`."""

    def __init__(self, handles: List[Any],
                 spec: Optional[RouterSpec] = None, log_fn=print):
        if not handles:
            raise ValueError("Router needs at least one engine handle")
        names = [h.name for h in handles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate engine names: {names}")
        self.spec = spec or RouterSpec()
        self.log = log_fn
        self.stats = RouterStats()
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {
            h.name: _Member(handle=h) for h in handles}
        self._backoff = faults.Backoff(base=self.spec.readmit_base_s,
                                       cap=self.spec.readmit_cap_s,
                                       seed=self.spec.seed)
        self._shed_backoff = faults.Backoff(base=0.05, cap=2.0,
                                            seed=self.spec.seed + 1)
        self._sheds_in_a_row = 0
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Router":
        self.probe_all()              # first verdicts before traffic
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-probe", daemon=True)
        self._probe_thread.start()
        return self

    def stop(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(5.0)
            self._probe_thread = None

    # -- membership reads ---------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def handle_for(self, name: str):
        return self._members[name].handle

    def members(self) -> List[Dict[str, Any]]:
        """Point-in-time per-engine view (stats/rollout surface)."""
        with self._lock:
            return [{
                "name": n, "healthy": m.healthy,
                "quarantined": m.quarantined, "strikes": m.strikes,
                "step": m.step, "in_flight": m.in_flight,
                "queue_depth": m.queue_depth,
                "dispatched": m.dispatched, "failed": m.failed,
                "quarantines": m.quarantines, "draining": m.draining,
            } for n, m in self._members.items()]

    def healthy_names(self) -> List[str]:
        with self._lock:
            return [n for n, m in self._members.items()
                    if m.healthy and not m.quarantined
                    and not m.draining]

    def engine_step(self, name: str) -> int:
        with self._lock:
            m = self._members.get(name)
            return m.step if m is not None else -1

    # -- runtime membership (autoscaler surface) ----------------------------
    def add_engine(self, handle) -> None:
        """Admit a new worker at runtime.  The caller must hand over a
        STARTED, warmed handle — the first probe below is a verdict,
        not a warmup, and an unhealthy join simply stays out of
        dispatch until it probes ok."""
        with self._lock:
            if handle.name in self._members:
                raise ValueError(
                    f"duplicate engine name: {handle.name!r}")
            self._members[handle.name] = _Member(handle=handle)
        self._probe_one(handle.name)   # first verdict before traffic
        self.stats.count("joins")
        self.log(f"fleet: engine {handle.name} joined "
                 f"(step {self.engine_step(handle.name)})")
        obs.emit_event("fleet.join", engine=handle.name,
                       step=self.engine_step(handle.name))

    def remove_engine(self, name: str, drain: bool = True,
                      timeout_s: float = 30.0) -> bool:
        """Retire a worker.  `drain=True` stops admissions immediately
        (the member is excluded from `_pick` under the same lock that
        admits) and waits for in-flight work — including held stream
        slots — to finish before dropping the member; returns whether
        the drain completed inside `timeout_s`.  Retirement is
        deliberate, so the member record (strikes, quarantine history)
        leaves with it — a re-added engine starts clean."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                return True            # already gone
            m.draining = True          # no new picks from here on
        drained = True
        if drain:
            deadline = time.monotonic() + float(timeout_s)
            while True:
                with self._lock:
                    mm = self._members.get(name)
                    busy = mm is not None and mm.in_flight > 0
                if not busy:
                    break
                if time.monotonic() >= deadline:
                    drained = False
                    break
                time.sleep(0.005)
        with self._lock:
            self._members.pop(name, None)
        self.stats.count("retires")
        self.log(f"fleet: engine {name} retired "
                 f"({'drained' if drained else 'drain timed out'})")
        obs.emit_event("fleet.retire", engine=name, drained=drained)
        return drained

    # -- probing ------------------------------------------------------------
    def _probe_loop(self) -> None:
        period = float(self.spec.probe_period_s)
        while not self._probe_stop.wait(period):
            self.probe_all()

    def probe_all(self) -> None:
        """One probe round over every member (also callable directly —
        tests and the rollout controller tighten timing with it)."""
        for name in self.names():
            self._probe_one(name)

    def _probe_one(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
        if m is None:
            return                    # retired while we iterated
        now = time.monotonic()
        if m.quarantined and now < m.bench_until:
            return                    # still benched; don't even probe
        try:
            with obs.span("router.probe", engine=name):
                h = m.handle.probe()
        except Exception as e:  # noqa: BLE001 — probe failure = strike
            self._strike(name, f"probe failed: {e}")
            return
        with self._lock:
            was_quarantined = m.quarantined
            m.last_health = h
            m.healthy = bool(h.get("ok"))
            m.step = int(h.get("step", -1))
            m.queue_depth = int(h.get("queue_depth", 0))
            if m.healthy:
                m.strikes = 0
                if was_quarantined:
                    m.quarantined = False
                    self.stats.count("readmissions")
        if m.healthy and was_quarantined:
            self.log(f"fleet: engine {name} readmitted after "
                     f"quarantine (probe ok, step {m.step})")
            obs.emit_event("fleet.readmit", engine=name, step=m.step)

    def _strike(self, name: str, why: str) -> None:
        """One probe/dispatch failure; `quarantine_after` consecutive
        strikes bench the engine for a Backoff delay that escalates
        with each consecutive quarantine (the ReplicaSet
        poisoned-round policy, serving-side).  A member retired
        mid-failure is not charged — its record is already gone."""
        with self._lock:
            m = self._members.get(name)
        if m is None:
            return
        with self._lock:
            m.strikes += 1
            m.healthy = False
            if m.strikes < self.spec.quarantine_after or m.quarantined:
                if m.quarantined:
                    # failed its readmission probe: bench it again,
                    # longer (the strike streak keeps growing)
                    m.quarantines += 1
                    m.bench_until = time.monotonic() + \
                        self._backoff.delay(m.quarantines - 1)
                return
            m.quarantined = True
            m.quarantines += 1
            delay = self._backoff.delay(m.quarantines - 1)
            m.bench_until = time.monotonic() + delay
            self.stats.count("quarantines")
        self.log(f"fleet: engine {name} quarantined for "
                 f"{delay:.2f}s ({why})")
        obs.emit_event("fleet.quarantine", engine=name, why=why,
                       bench_s=round(delay, 4))

    # -- dispatch -----------------------------------------------------------
    def _pick(self, exclude: set) -> Optional[str]:
        """Least-loaded healthy engine (in-flight + probed queue
        depth), excluding already-tried ones."""
        with self._lock:
            cands = [(m.in_flight + m.queue_depth, n)
                     for n, m in self._members.items()
                     if n not in exclude and m.healthy
                     and not m.quarantined and not m.draining]
            if not cands:
                return None
            _, name = min(cands)
            self._members[name].in_flight += 1
            return name

    def _release(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.in_flight -= 1

    def route(self, mode: str, tokens,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        """Dispatch one request; retries engine failures on other
        engines and sheds (`Overloaded` + Retry-After) only when no
        engine can take it.  The result carries `engine`, the member
        that served it."""
        if timeout is None:
            timeout = self.spec.request_timeout_s
        t0 = time.monotonic()
        self.stats.count("routed")
        budget = (self.spec.max_attempts
                  if self.spec.max_attempts > 0 else len(self._members))
        tried: set = set()
        saturated = 0
        with obs.span("router.dispatch", mode=mode) as sp:
            for attempt in range(budget):
                name = self._pick(tried)
                if name is None:
                    break
                tried.add(name)
                with self._lock:
                    m = self._members.get(name)
                if m is None:          # force-retired between pick/use
                    self.stats.count("retried")
                    continue
                try:
                    faults.maybe_fault("fleet.dispatch")
                    out = m.handle.request(mode, tokens,
                                           timeout=timeout)
                except Overloaded:
                    # load, not failure: no strike, try a sibling
                    saturated += 1
                    self.stats.count("retried")
                    continue
                except (DeadlineExpired, TimeoutError):
                    # the request's own deadline died inside the
                    # engine; retrying elsewhere would only blow it
                    # further — surface it
                    self.stats.count("failed")
                    raise
                except ValueError:
                    self.stats.count("failed")
                    raise          # unservable request, not a failure
                except Exception as e:  # noqa: BLE001 — engine failure
                    with self._lock:
                        m.failed += 1
                    self._strike(name, f"dispatch failed: {e}")
                    self.stats.count("retried")
                    continue
                finally:
                    self._release(name)
                with self._lock:
                    m.dispatched += 1
                    self._sheds_in_a_row = 0
                self.stats.count("completed")
                self.stats.observe_latency(time.monotonic() - t0)
                out["engine"] = name
                sp.set(engine=name, attempts=attempt + 1)
                return out
            # nothing left to try: the fleet is saturated or down
            why = ("fleet saturated" if saturated
                   else "no healthy engine available"
                   if not tried else
                   f"all {len(tried)} reachable engine(s) failed")
            self._shed(why)

    def route_stream(self, tokens, timeout: Optional[float] = None,
                     max_new: Optional[int] = None):
        """Streaming dispatch: pick an engine exactly like `route`,
        but return its token-event iterator instead of a buffered
        result.  Retry-on-other-engine applies ONLY until the chosen
        engine admits the stream (its `request_stream` returning is
        the first-byte commit) — after that a failure surfaces to the
        caller, because tokens may already be on the wire and a
        replay would duplicate them.  The engine's in-flight slot is
        held until the consumer exhausts (or abandons) the stream."""
        if timeout is None:
            timeout = self.spec.request_timeout_s
        t0 = time.monotonic()
        self.stats.count("routed")
        budget = (self.spec.max_attempts
                  if self.spec.max_attempts > 0 else len(self._members))
        tried: set = set()
        saturated = 0
        for _attempt in range(budget):
            name = self._pick(tried)
            if name is None:
                break
            tried.add(name)
            with self._lock:
                m = self._members.get(name)
            if m is None:              # force-retired between pick/use
                self.stats.count("retried")
                continue
            try:
                faults.maybe_fault("fleet.dispatch")
                stream = m.handle.request_stream(tokens,
                                                 timeout=timeout,
                                                 max_new=max_new)
            except Overloaded:
                self._release(name)
                saturated += 1
                self.stats.count("retried")
                continue
            except (DeadlineExpired, TimeoutError, ValueError):
                self._release(name)
                self.stats.count("failed")
                raise
            except Exception as e:  # noqa: BLE001 — engine failure
                self._release(name)
                with self._lock:
                    m.failed += 1
                self._strike(name, f"stream dispatch failed: {e}")
                self.stats.count("retried")
                continue
            # committed to this engine: wrap the stream so the
            # in-flight accounting survives however the consumer
            # finishes (exhaustion, error, or abandonment)
            return self._wrap_stream(name, stream, t0)
        why = ("fleet saturated" if saturated
               else "no healthy engine available"
               if not tried else
               f"all {len(tried)} reachable engine(s) failed")
        self._shed(why)

    def _wrap_stream(self, name: str, stream, t0: float):
        with self._lock:
            m = self._members.get(name)

        def gen():
            finished = False
            try:
                for ev in stream:
                    if ev.get("done"):
                        ev.setdefault("engine", name)
                        finished = True
                    yield ev
            finally:
                self._release(name)
                if finished:
                    with self._lock:
                        if m is not None:
                            m.dispatched += 1
                        self._sheds_in_a_row = 0
                    self.stats.count("completed")
                    self.stats.observe_latency(time.monotonic() - t0)
                else:
                    self.stats.count("failed")
        return gen()

    def _shed(self, why: str) -> None:
        with self._lock:
            self._sheds_in_a_row += 1
            attempt = self._sheds_in_a_row
        self.stats.count("shed")
        retry = self._shed_backoff.delay(attempt - 1)
        obs.emit_event("serve.shed", why=f"router: {why}",
                       retry_after=round(retry, 4))
        raise Overloaded(f"request shed ({why}); retry after "
                         f"{retry:.3f}s", retry_after=retry)

    # -- rollout support ----------------------------------------------------
    def pick_canary(self) -> Optional[str]:
        """The engine to canary a new checkpoint on: healthy and
        carrying the LEAST traffic — a bad fingerprint should touch as
        little of the fleet's load as possible."""
        with self._lock:
            cands = [(m.in_flight + m.queue_depth, n)
                     for n, m in self._members.items()
                     if m.healthy and not m.quarantined
                     and not m.draining]
        return min(cands)[1] if cands else None

    def snapshot(self) -> Dict[str, Any]:
        out = self.stats.snapshot()
        out["engines"] = self.members()
        out["healthy_engines"] = len(self.healthy_names())
        return out
