"""Adversarial open-loop traffic harness: scenario load generation.

"Handles heavy traffic" is a claim until a load generator can refute
it.  The critical property here is OPEN-LOOP arrivals: each request
fires at its Poisson-scheduled instant whether or not earlier requests
have completed.  A closed-loop client (issue → wait → issue) slows
down exactly when the server does, so measured latency self-limits
and overload is invisible; an open-loop generator keeps offering load,
which is what a million independent users do.

Scenarios compose from `Phase`s:

    steady(...)       constant-rate Poisson arrivals
    ramp(...)         rate sweeps linearly start→end (diurnal rise)
    flash_crowd(...)  a step to k× the base rate (the retweet moment)
    diurnal(...)      ramp up → plateau → ramp down, in one call

Each `Phase` also carries the request-shape mix — long-tail prompt
lengths and max_new choices with weights — plus a `stream_p` fraction
of streaming requests, an optional `slow_reader_s` per-token consumer
delay (the client on hotel wifi that holds a stream slot open), and
an `on_start` hook for chaos legs (kill an engine mid-ramp, or
`stall_chaos(...)` to turn one replica into a straggler), and a QoS
`priorities`/`priority_weights` mix for brownout legs — reports then
break offered/completed/shed/p95 down per class.

`TrafficGen.run(phases)` records, per phase and in total: offered vs
completed load, sheds (`Overloaded` — the server protecting itself,
not a failure), failures (everything else — always a bug), harness
drops (the `max_outstanding` safety cap; counted, never silent), and
p50/p95/p99 completion latency.  Completions are attributed to the
phase that OFFERED them, so a flash crowd's backlog can't launder its
latency into the decay phase.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import qos
from .batcher import Overloaded


@dataclass(frozen=True)
class Phase:
    """One scenario leg: an arrival process plus a request-shape mix.
    `rate_end_rps` turns the leg into a linear ramp; weights need not
    sum to 1 (normalized at sample time)."""
    name: str
    duration_s: float
    rate_rps: float
    rate_end_rps: Optional[float] = None
    prompt_lens: Tuple[int, ...] = (4, 8)
    prompt_weights: Optional[Tuple[float, ...]] = None
    max_new: Tuple[int, ...] = (4,)
    max_new_weights: Optional[Tuple[float, ...]] = None
    stream_p: float = 0.0          # fraction routed as streams
    slow_reader_s: float = 0.0     # per-token consumer stall (streams)
    priorities: Tuple[str, ...] = ("interactive",)   # QoS class mix
    priority_weights: Optional[Tuple[float, ...]] = None
    # multi-tenant mix: each arrival is attributed to one tenant id
    # (weights normalized at sample time, like the other mixes) and
    # the report breaks offered/completed/shed/p95 down per tenant —
    # the isolation gate's raw data
    tenants: Tuple[str, ...] = ("default",)
    tenant_weights: Optional[Tuple[float, ...]] = None
    on_start: Optional[Callable[[], None]] = None   # chaos hook

    def __post_init__(self):
        if float(self.duration_s) <= 0:
            raise ValueError(f"phase {self.name!r}: duration_s must "
                             f"be > 0")
        if float(self.rate_rps) <= 0:
            raise ValueError(f"phase {self.name!r}: rate_rps must "
                             f"be > 0")
        if not 0 <= float(self.stream_p) <= 1:
            raise ValueError(f"phase {self.name!r}: stream_p must be "
                             f"in [0, 1]")
        for p in self.priorities:
            if p not in qos.PRIORITIES:
                raise ValueError(f"phase {self.name!r}: unknown "
                                 f"priority {p!r} (want one of "
                                 f"{qos.PRIORITIES})")
        if not self.tenants:
            raise ValueError(f"phase {self.name!r}: tenants must "
                             f"name at least one tenant")
        if self.tenant_weights is not None and \
                len(self.tenant_weights) != len(self.tenants):
            raise ValueError(f"phase {self.name!r}: tenant_weights "
                             f"must match tenants "
                             f"({len(self.tenant_weights)} vs "
                             f"{len(self.tenants)})")

    def rate_at(self, frac: float) -> float:
        """Instantaneous arrival rate `frac` of the way through."""
        if self.rate_end_rps is None:
            return float(self.rate_rps)
        return float(self.rate_rps) + (
            float(self.rate_end_rps) - float(self.rate_rps)) * frac


# -- scenario builders ------------------------------------------------------

def steady(name: str, duration_s: float, rate_rps: float,
           **kw) -> Phase:
    return Phase(name=name, duration_s=duration_s, rate_rps=rate_rps,
                 **kw)


def ramp(name: str, duration_s: float, start_rps: float,
         end_rps: float, **kw) -> Phase:
    return Phase(name=name, duration_s=duration_s, rate_rps=start_rps,
                 rate_end_rps=end_rps, **kw)


def flash_crowd(name: str, duration_s: float, base_rps: float,
                k: float = 5.0, **kw) -> Phase:
    """A step to k× the base rate — the load a ramp-tuned fleet has
    not provisioned for yet."""
    return Phase(name=name, duration_s=duration_s,
                 rate_rps=base_rps * float(k), **kw)


def diurnal(base_rps: float, peak_rps: float, rise_s: float,
            plateau_s: float, fall_s: float, **kw) -> List[Phase]:
    return [ramp("diurnal-rise", rise_s, base_rps, peak_rps, **kw),
            steady("diurnal-plateau", plateau_s, peak_rps, **kw),
            ramp("diurnal-fall", fall_s, peak_rps, base_rps, **kw)]


# -- generator --------------------------------------------------------------

class _PhaseLog:
    def __init__(self, name: str):
        self.name = name
        self.offered = 0
        self.completed = 0
        self.shed = 0
        self.failed = 0
        self.dropped_harness = 0
        self.latencies: List[float] = []
        self.errors: List[str] = []
        # per-QoS-class attribution (the brownout gate's raw data)
        self.offered_by_class: Dict[str, int] = {}
        self.completed_by_class: Dict[str, int] = {}
        self.shed_by_class: Dict[str, int] = {}
        self.lat_by_class: Dict[str, List[float]] = {}
        # per-tenant attribution (the isolation gate's raw data)
        self.offered_by_tenant: Dict[str, int] = {}
        self.completed_by_tenant: Dict[str, int] = {}
        self.shed_by_tenant: Dict[str, int] = {}
        self.lat_by_tenant: Dict[str, List[float]] = {}
        # per-stream delivery audit (the failover exactly-once gate):
        # duplicate/out-of-order indices and spliced terminals seen by
        # the CLIENT side of the harness
        self.stream_resumed = 0
        self.stream_dup = 0
        self.stream_gap = 0


def stall_chaos(fleet, name: Optional[str] = None,
                stall_s: float = 0.25) -> Callable[[], None]:
    """Chaos `on_start` hook: latch a per-step decode stall onto one
    LOCAL engine (`InferenceEngine.set_stall`) — the slow-replica leg
    the hedging gate runs against.  With `name=None` the
    lexicographically LAST active member is stalled: the Router's
    least-loaded tie-break prefers earlier names, so the straggler
    keeps eating its share of traffic through load imbalance rather
    than winning every pick."""
    def hook():
        target = name
        if target is None:
            members = sorted(m["name"]
                             for m in fleet.router.members()
                             if not m.get("draining"))
            target = members[-1] if members else None
        if target is None:
            return
        eng = getattr(fleet.router.handle_for(target), "engine", None)
        if eng is None:
            raise RuntimeError(f"stall_chaos: {target!r} is not a "
                               f"local engine (no set_stall)")
        eng.set_stall(stall_s)
    return hook


def kill_chaos(fleet, name: Optional[str] = None,
               delay_s: float = 0.0) -> Callable[[], None]:
    """Chaos `on_start` hook: crash one LOCAL engine
    (`LocalEngineHandle.kill`) — the mid-stream failover leg runs
    against this.  With `name=None` the lexicographically FIRST
    active member dies (the Router's least-loaded tie-break prefers
    earlier names, so the victim is holding live streams when it
    goes).  `delay_s` arms the kill on a timer so streams admitted at
    phase start are mid-decode when it fires."""
    def hook():
        target = name
        if target is None:
            members = sorted(m["name"]
                             for m in fleet.router.members()
                             if not m.get("draining"))
            target = members[0] if members else None
        if target is None:
            return

        def kill():
            h = fleet.router.handle_for(target)
            if not hasattr(h, "kill"):
                raise RuntimeError(f"kill_chaos: {target!r} has no "
                                   f"kill() (not a local handle)")
            h.kill()
        if delay_s > 0:
            threading.Timer(float(delay_s), kill).start()
        else:
            kill()
    return hook


class TrafficGen:
    """Open-loop Poisson load against a fleet-shaped target.

    `request_fn(tokens)` runs one buffered request (e.g.
    `fleet.generate`); `stream_fn(tokens, max_new)` (optional) returns
    a token-event iterator (e.g. `fleet.generate_stream`).  Both may
    raise `Overloaded` (counted as shed) — anything else is a failure.
    `max_outstanding` bounds harness threads: an arrival past the cap
    is counted `dropped_harness`, never silently skipped — the report
    stays honest about the load actually offered."""

    def __init__(self, request_fn: Callable[[Any], Any],
                 stream_fn: Optional[Callable[..., Any]] = None,
                 vocab: int = 64, seed: int = 0,
                 max_outstanding: int = 512, log_fn=print):
        self.request_fn = request_fn
        self.stream_fn = stream_fn
        self.vocab = int(vocab)
        self.seed = int(seed)
        self.max_outstanding = int(max_outstanding)
        self.log = log_fn
        self._lock = threading.Lock()
        self._outstanding = 0
        self._threads: List[threading.Thread] = []

    # -- one request --------------------------------------------------------
    def _sample(self, rng, choices, weights) -> int:
        if weights is None:
            return int(rng.choice(list(choices)))
        w = np.asarray(weights, dtype=np.float64)
        return int(rng.choice(list(choices), p=w / w.sum()))

    def _pick_priority(self, rng, phase: Phase) -> str:
        if len(phase.priorities) == 1:
            return phase.priorities[0]
        if phase.priority_weights is None:
            return str(rng.choice(list(phase.priorities)))
        w = np.asarray(phase.priority_weights, dtype=np.float64)
        return str(rng.choice(list(phase.priorities), p=w / w.sum()))

    def _pick_tenant(self, rng, phase: Phase) -> str:
        if len(phase.tenants) == 1:
            return phase.tenants[0]
        if phase.tenant_weights is None:
            return str(rng.choice(list(phase.tenants)))
        w = np.asarray(phase.tenant_weights, dtype=np.float64)
        return str(rng.choice(list(phase.tenants), p=w / w.sum()))

    def _fire(self, phase: Phase, log: _PhaseLog, rng_seed: int) -> None:
        rng = np.random.default_rng(rng_seed)
        plen = self._sample(rng, phase.prompt_lens,
                            phase.prompt_weights)
        mnew = self._sample(rng, phase.max_new, phase.max_new_weights)
        tokens = rng.integers(1, self.vocab, size=plen).astype(np.int32)
        as_stream = (self.stream_fn is not None
                     and rng.random() < float(phase.stream_p))
        pri = self._pick_priority(rng, phase)
        ten = self._pick_tenant(rng, phase)
        # Back-compat: plain `request_fn(tokens)` targets (tests wrap
        # bare lambdas) only see a kwarg when the phase actually
        # mixes classes/tenants — "interactive"/"default" is every
        # layer's default.
        kw: Dict[str, Any] = {} if pri == "interactive" \
            else {"priority": pri}
        if ten != "default":
            kw["tenant"] = ten
        with self._lock:
            log.offered_by_class[pri] = \
                log.offered_by_class.get(pri, 0) + 1
            log.offered_by_tenant[ten] = \
                log.offered_by_tenant.get(ten, 0) + 1
        t0 = time.monotonic()
        try:
            if as_stream:
                want_i = 0
                for ev in self.stream_fn(tokens, max_new=mnew, **kw):
                    if "token" in ev and not ev.get("done"):
                        i = int(ev.get("i", want_i))
                        if i < want_i:
                            with self._lock:
                                log.stream_dup += 1
                        elif i > want_i:
                            with self._lock:
                                log.stream_gap += 1
                            want_i = i + 1
                        else:
                            want_i += 1
                    elif ev.get("done") and ev.get("spliced"):
                        with self._lock:
                            log.stream_resumed += 1
                    if phase.slow_reader_s > 0 and "token" in ev:
                        time.sleep(phase.slow_reader_s)
            else:
                self.request_fn(tokens, **kw)
        except Overloaded:
            with self._lock:
                log.shed += 1
                log.shed_by_class[pri] = \
                    log.shed_by_class.get(pri, 0) + 1
                log.shed_by_tenant[ten] = \
                    log.shed_by_tenant.get(ten, 0) + 1
            return
        except Exception as e:  # noqa: BLE001 — non-shed failure
            with self._lock:
                log.failed += 1
                if len(log.errors) < 5:
                    log.errors.append(f"{type(e).__name__}: {e}")
            return
        lat = time.monotonic() - t0
        with self._lock:
            log.completed += 1
            log.latencies.append(lat)
            log.completed_by_class[pri] = \
                log.completed_by_class.get(pri, 0) + 1
            log.lat_by_class.setdefault(pri, []).append(lat)
            log.completed_by_tenant[ten] = \
                log.completed_by_tenant.get(ten, 0) + 1
            log.lat_by_tenant.setdefault(ten, []).append(lat)

    def _spawn(self, phase: Phase, log: _PhaseLog, seed: int) -> None:
        with self._lock:
            if self._outstanding >= self.max_outstanding:
                log.dropped_harness += 1
                return
            self._outstanding += 1
            log.offered += 1

        def run():
            try:
                self._fire(phase, log, seed)
            finally:
                with self._lock:
                    self._outstanding -= 1

        t = threading.Thread(target=run, daemon=True,
                             name=f"traffic-{phase.name}")
        self._threads.append(t)
        t.start()

    # -- the open loop ------------------------------------------------------
    def run(self, phases: Sequence[Phase],
            drain_timeout_s: float = 60.0) -> Dict[str, Any]:
        """Drive every phase in order, then wait (bounded) for the
        tail of in-flight requests.  Arrivals NEVER wait on
        completions — the defining open-loop property."""
        rng = np.random.default_rng(self.seed)
        logs: List[_PhaseLog] = []
        seq = 0
        for phase in phases:
            log = _PhaseLog(phase.name)
            logs.append(log)
            if phase.on_start is not None:
                try:
                    phase.on_start()
                except Exception as e:  # noqa: BLE001 — chaos hook
                    self.log(f"traffic: on_start hook for "
                             f"{phase.name!r} failed: {e}")
            t0 = time.monotonic()
            end = t0 + float(phase.duration_s)
            next_t = t0
            while True:
                now = time.monotonic()
                if next_t >= end:
                    break
                if next_t > now:
                    time.sleep(min(next_t - now, 0.05))
                    continue
                self._spawn(phase, log, self.seed + seq)
                seq += 1
                frac = (next_t - t0) / float(phase.duration_s)
                rate = max(phase.rate_at(frac), 1e-6)
                next_t += float(rng.exponential(1.0 / rate))
            self.log(f"traffic: phase {phase.name!r} offered "
                     f"{log.offered} over {phase.duration_s:.1f}s")
        deadline = time.monotonic() + float(drain_timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if self._outstanding == 0:
                    break
            time.sleep(0.02)
        with self._lock:
            undrained = self._outstanding
        if undrained:
            self.log(f"traffic: {undrained} request(s) still in "
                     f"flight after {drain_timeout_s}s drain")
        self._threads = [t for t in self._threads if t.is_alive()]
        return self._report(logs, phases)

    # -- reporting ----------------------------------------------------------
    @staticmethod
    def _quantile(lats: List[float], q: float) -> Optional[float]:
        if not lats:
            return None
        s = sorted(lats)
        return round(s[min(int(q * len(s)), len(s) - 1)] * 1e3, 3)

    def _by_class(self, log: _PhaseLog) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for pri in sorted(set(log.offered_by_class)
                          | set(log.shed_by_class)
                          | set(log.completed_by_class)):
            lats = log.lat_by_class.get(pri, [])
            out[pri] = {
                "offered": log.offered_by_class.get(pri, 0),
                "completed": log.completed_by_class.get(pri, 0),
                "shed": log.shed_by_class.get(pri, 0),
                "p95_ms": self._quantile(lats, 0.95),
            }
        return out

    def _by_tenant(self, log: _PhaseLog) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for ten in sorted(set(log.offered_by_tenant)
                          | set(log.shed_by_tenant)
                          | set(log.completed_by_tenant)):
            lats = log.lat_by_tenant.get(ten, [])
            out[ten] = {
                "offered": log.offered_by_tenant.get(ten, 0),
                "completed": log.completed_by_tenant.get(ten, 0),
                "shed": log.shed_by_tenant.get(ten, 0),
                "p95_ms": self._quantile(lats, 0.95),
            }
        return out

    def _report(self, logs: List[_PhaseLog],
                phases: Sequence[Phase]) -> Dict[str, Any]:
        out_phases = []
        tot = _PhaseLog("total")
        for log, phase in zip(logs, phases):
            with self._lock:
                lats = list(log.latencies)
                row = {
                    "name": log.name,
                    "duration_s": float(phase.duration_s),
                    "offered": log.offered,
                    "completed": log.completed,
                    "shed": log.shed,
                    "failed": log.failed,
                    "dropped_harness": log.dropped_harness,
                    "qps_offered": round(
                        log.offered / float(phase.duration_s), 3),
                    "qps_completed": round(
                        log.completed / float(phase.duration_s), 3),
                    "p50_ms": self._quantile(lats, 0.50),
                    "p95_ms": self._quantile(lats, 0.95),
                    "p99_ms": self._quantile(lats, 0.99),
                    "stream_resumed": log.stream_resumed,
                    "stream_dup": log.stream_dup,
                    "stream_gap": log.stream_gap,
                    "by_class": self._by_class(log),
                    "by_tenant": self._by_tenant(log),
                    "errors": list(log.errors),
                }
            out_phases.append(row)
            tot.offered += log.offered
            tot.completed += log.completed
            tot.shed += log.shed
            tot.failed += log.failed
            tot.dropped_harness += log.dropped_harness
            tot.stream_resumed += log.stream_resumed
            tot.stream_dup += log.stream_dup
            tot.stream_gap += log.stream_gap
            tot.latencies.extend(lats)
            tot.errors.extend(log.errors)
            with self._lock:
                for d_tot, d_log in (
                        (tot.offered_by_class, log.offered_by_class),
                        (tot.completed_by_class,
                         log.completed_by_class),
                        (tot.shed_by_class, log.shed_by_class),
                        (tot.offered_by_tenant,
                         log.offered_by_tenant),
                        (tot.completed_by_tenant,
                         log.completed_by_tenant),
                        (tot.shed_by_tenant, log.shed_by_tenant)):
                    for pri, n in d_log.items():
                        d_tot[pri] = d_tot.get(pri, 0) + n
                for pri, ls in log.lat_by_class.items():
                    tot.lat_by_class.setdefault(pri, []).extend(ls)
                for ten, ls in log.lat_by_tenant.items():
                    tot.lat_by_tenant.setdefault(ten, []).extend(ls)
        return {
            "phases": out_phases,
            "totals": {
                "offered": tot.offered,
                "completed": tot.completed,
                "shed": tot.shed,
                "failed": tot.failed,
                "dropped_harness": tot.dropped_harness,
                "shed_rate": round(
                    tot.shed / max(tot.offered, 1), 4),
                "p50_ms": self._quantile(tot.latencies, 0.50),
                "p95_ms": self._quantile(tot.latencies, 0.95),
                "p99_ms": self._quantile(tot.latencies, 0.99),
                "stream_resumed": tot.stream_resumed,
                "stream_dup": tot.stream_dup,
                "stream_gap": tot.stream_gap,
                "by_class": self._by_class(tot),
                "by_tenant": self._by_tenant(tot),
                "errors": tot.errors[:10],
            },
        }
