"""Threaded serving frontend: stdlib HTTP plus an in-process client
API over the same engine + batcher.

The HTTP layer is deliberately thin — the transport never touches the
hot path ("RPC Considered Harmful"): a handler thread only parses
JSON, calls `MicroBatcher.submit` (or `ContinuousScheduler.submit`
under `cb=on`), and parks on the request's `Ticket` (or drains its
`StreamTicket`); all device work happens on the single dispatch
thread through compiled programs.  In-process callers
(`InferenceServer.generate` / `.predict`, used by tests and the bench
smoke) take the same submit/wait path, so both frontends share one
admission-control, batching, and stats story.

Endpoints:
    POST /generate  {"tokens": [ints], "timeout": s?}   -> {"tokens",
                    "step", "bucket", "latency_ms"}; under cb=on the
                    result carries "finish"/"slots" instead of
                    "bucket", and {"stream": true} switches the
                    response to chunked ndjson — one {"token": t}
                    line per decode step, then a terminal
                    {"done": true, "tokens", "finish", "step",
                    "latency_ms"} line (admission errors keep their
                    status codes; mid-stream failures become a
                    terminal {"error": ...} line)
    POST /predict   {"tokens": [ints], "timeout": s?}   -> {"logprobs",
                    "step", "bucket", "latency_ms"}
    GET  /stats     ServeStats.snapshot() incl. served params step
    GET  /metrics   Prometheus text exposition of the same counters
                    (each server owns a MetricsRegistry; the collector
                    reads ServeStats.snapshot(), so /metrics and /stats
                    agree by construction)
    GET  /healthz   engine.health(): 200 {"ok": true, ...} only while
                    the engine is actually healthy; 503 with
                    {"ok": false, "status": "degraded", "reasons"}
                    after `degraded_after` consecutive failed batches
                    or a refused/failed reload leaving stale params —
                    the signal the fleet router dispatches on
    GET  /trace     this process's span ring as a Perfetto dict
                    (obs.trace_dump(); empty when tracing is off) —
                    the buffer obs/collect.py pulls to merge fleet
                    traces into one timeline
    POST /admin/reload  {"step": n?} -> engine.reload_to(step): the
                    fleet rollout controller's command channel for
                    remote (subprocess) engine members; returns
                    {"outcome", "step"}
Status mapping: 503 + Retry-After on `Overloaded` (shed), 504 on
deadline/timeout, 400 on a malformed request, 500 on a failed batch.

A daemon poll thread calls `engine.poll_reload()` every
`spec.reload_poll_s` — hot reloads (and their counted degradations)
happen without any frontend involvement.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from .. import obs
from ..obs import perf
from ..obs.metrics import MetricsRegistry
from . import qos, wire
from .batcher import DeadlineExpired, MicroBatcher, Overloaded
from .engine import InferenceEngine, ServeSpec  # noqa: F401 (re-export)
from .scheduler import ContinuousScheduler, StreamTicket
from .stats import ServeStats  # noqa: F401 (re-export: stats mold)
from .tenancy import TenantRegistry


class InferenceServer:
    """Owns the engine, the batcher, the reload poll thread, and
    (optionally) the HTTP frontend.  `start()` loads + warms the
    engine and spins everything up; `stop()` tears it down in reverse
    order.  Usable as a context manager."""

    def __init__(self, engine: InferenceEngine,
                 host: str = "127.0.0.1", port: int = 0,
                 http: bool = True, warmup_modes=("generate",),
                 log_fn=print,
                 tenancy: Optional[TenantRegistry] = None,
                 wire_on: bool = False, wire_port: int = 0):
        self.engine = engine
        self.stats = engine.stats
        # ONE tenant registry per server, shared by both admission
        # paths — quotas and brownout overrides agree by construction
        self.tenancy = tenancy if tenancy is not None \
            else TenantRegistry()
        self.batcher = MicroBatcher(engine, log_fn=log_fn,
                                    tenancy=self.tenancy)
        # cb=on: generate leaves the static buckets for the
        # continuous-batching scheduler (predict stays on the
        # batcher's bucket path)
        self.scheduler = (ContinuousScheduler(engine, log_fn=log_fn,
                                              tenancy=self.tenancy)
                          if engine.spec.cb_on else None)
        self.log = log_fn
        # per-server registry (not process-global: parallel tests each
        # get their own) backing the /metrics Prometheus endpoint
        self.metrics = MetricsRegistry()
        self.stats.register_into(self.metrics)
        # performance observatory (compiles/HBM/cost/readiness) + the
        # process-level collector (RSS/threads/fds/uptime) export on
        # every /metrics endpoint — a leaking engine must be visible
        perf.register_into(self.metrics)
        perf.register_process_into(self.metrics)
        # process-wide binary-transport counters (serve/wire.py) —
        # same process-global idiom as perf: every server's /metrics
        # shows the one wire story
        wire.register_into(self.metrics)
        self._host, self._port = host, port
        # binary framed listener beside the HTTP frontend; HTTP stays
        # the always-on debug-and-negotiation surface (/healthz
        # advertises the wire port)
        self._wire_wanted = bool(wire_on)
        self._wire_port = int(wire_port)
        self._wire: Optional[wire.BinaryTransportServer] = None
        self._http_wanted = http
        self._warmup_modes = tuple(warmup_modes)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "InferenceServer":
        if self.engine.params is None or (
                self.engine.ckpt is not None
                and self.engine.params_step < 0):
            # no params yet, or constructor-fallback params with a
            # workspace that may hold something better: load() prefers
            # the latest healthy snapshot and keeps the fallback only
            # when nothing is restorable
            self.engine.load()
        n = self.engine.warmup(self._warmup_modes)
        shape = (f"cb slots={self.engine.spec.cb_slots} "
                 f"blocks={self.engine.spec.cb_pool_blocks}"
                 if self.engine.spec.cb_on
                 else f"buckets {self.engine.spec.buckets}")
        self.log(f"serve: warmed {n} program(s) for {shape}, serving "
                 f"checkpoint step {self.engine.params_step}")
        self.batcher.start()
        if self.scheduler is not None:
            self.scheduler.start()
        self._poll_stop.clear()
        if not self.engine.pinned:
            # pinned (fleet-member) engines never self-reload — the
            # rollout controller drives reload_to explicitly
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="serve-reload",
                daemon=True)
            self._poll_thread.start()
        if self._http_wanted:
            self._httpd = ThreadingHTTPServer(
                (self._host, self._port), _make_handler(self))
            self._httpd.daemon_threads = True
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever, name="serve-http",
                daemon=True)
            self._http_thread.start()
            self.log(f"serve: http on {self.address[0]}:"
                     f"{self.address[1]}")
        if self._wire_wanted:
            self._wire = wire.BinaryTransportServer(
                self, host=self._host, port=self._wire_port,
                log_fn=self.log).start()
        return self

    def stop(self) -> None:
        if self._wire is not None:
            self._wire.stop()
            self._wire = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._http_thread = None
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(5.0)
            self._poll_thread = None
        if self.scheduler is not None:
            self.scheduler.stop()
        self.batcher.stop()

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def address(self):
        """(host, port) of the HTTP frontend (port resolved when the
        constructor asked for 0), or None without HTTP."""
        return self._httpd.server_address if self._httpd else None

    @property
    def wire_address(self):
        """(host, port) of the binary framed listener, or None when
        the server speaks HTTP only."""
        return self._wire.address if self._wire else None

    def _poll_loop(self) -> None:
        """Supervised reload poll: `poll_reload` already contains the
        expected degradations (failed reloads count + keep serving),
        but an UNEXPECTED exception here used to kill the daemon
        thread silently — the engine then served stale params forever
        behind a healthy /healthz.  Now a death is counted
        (`reload_poll_deaths`), the loop restarts itself after a
        Backoff delay, and `engine.health()` degrades once the death
        streak crosses `degraded_after` (the router stops dispatching
        to a poller that cannot stay alive)."""
        from ..utils import faults
        period = max(float(self.engine.spec.reload_poll_s), 0.01)
        backoff = faults.Backoff(base=period, cap=max(period * 16, 5.0),
                                 seed=0)
        while not self._poll_stop.wait(period):
            try:
                self.engine.poll_reload()
                self.engine.note_poll_ok()
            except Exception as e:  # noqa: BLE001 — supervised restart
                streak = self.engine.note_poll_death()
                self.stats.count("reload_poll_deaths")
                self.log(f"warning: reload poll died "
                         f"({type(e).__name__}: {e}); restarting "
                         f"(streak {streak})")
                if self._poll_stop.wait(backoff.delay(streak - 1)):
                    return

    # -- in-process client API ---------------------------------------------
    def generate(self, tokens, timeout: Optional[float] = None,
                 max_new: Optional[int] = None,
                 deadline: Optional[float] = None,
                 priority: str = "interactive",
                 tenant: Optional[str] = None,
                 cancel_event: Optional[threading.Event] = None
                 ) -> Dict[str, Any]:
        """Submit one prompt and block for the decoded continuation.
        Raises Overloaded / DeadlineExpired / TimeoutError exactly as
        the HTTP layer maps them.  `max_new` caps this request's
        generation under cb; the static bucket path decodes the full
        spec.max_new_tokens regardless (the whole batch shares one
        compiled program) and only trims the reply.  `deadline`
        (absolute monotonic) is the request's end-to-end budget and
        wins over `timeout`; `priority` / `cancel_event` flow to
        admission (serve/qos.py)."""
        t0 = time.monotonic()
        if self.scheduler is not None:
            ticket = self.scheduler.submit(
                tokens, timeout=timeout, max_new=max_new,
                deadline=deadline, priority=priority, tenant=tenant,
                cancel_event=cancel_event)
        else:
            ticket = self.batcher.submit(
                tokens, mode="generate", timeout=timeout,
                deadline=deadline, priority=priority, tenant=tenant,
                cancel_event=cancel_event)
        out = ticket.wait(self._wait_budget(timeout, deadline))
        if self.scheduler is None and max_new is not None \
                and int(max_new) >= 1:
            out["tokens"] = out["tokens"][:int(max_new)]
        out["latency_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        return out

    def generate_stream(self, tokens,
                        timeout: Optional[float] = None,
                        max_new: Optional[int] = None,
                        deadline: Optional[float] = None,
                        priority: str = "interactive",
                        tenant: Optional[str] = None,
                        cancel_event: Optional[threading.Event] = None,
                        resume_from: int = 0) -> StreamTicket:
        """Streaming admission (cb only): returns the request's
        `StreamTicket` — iterate `.tokens()` / `.events()` for tokens
        as slots produce them.  `resume_from=n` re-admits a failover
        continuation: the last n prompt tokens are an already-emitted
        prefix, the ticket numbers its output from n.  Raises
        RuntimeError when the server is not running continuous
        batching."""
        if self.scheduler is None:
            raise RuntimeError("streaming generate needs cb=on in the "
                               "serve spec")
        return self.scheduler.submit(
            tokens, timeout=timeout, max_new=max_new,
            deadline=deadline, priority=priority, tenant=tenant,
            cancel_event=cancel_event, resume_from=resume_from)

    def predict(self, tokens,
                timeout: Optional[float] = None,
                deadline: Optional[float] = None,
                priority: str = "interactive",
                tenant: Optional[str] = None,
                cancel_event: Optional[threading.Event] = None
                ) -> Dict[str, Any]:
        """Next-token log-probs for one prompt (LM scoring)."""
        t0 = time.monotonic()
        ticket = self.batcher.submit(
            tokens, mode="predict", timeout=timeout,
            deadline=deadline, priority=priority, tenant=tenant,
            cancel_event=cancel_event)
        out = ticket.wait(self._wait_budget(timeout, deadline))
        out["latency_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        return out

    def _wait_budget(self, timeout: Optional[float],
                     deadline: Optional[float] = None) -> float:
        # queue deadline + dispatch slack: wait() must outlive the
        # in-queue deadline so expiry surfaces as DeadlineExpired, not
        # a bare TimeoutError.  qos.transport_budget clamps the slack
        # to the remaining deadline so the wait can't outlive the
        # client's budget by a flat 30s.
        return qos.transport_budget(
            deadline, timeout, self.engine.spec.request_timeout_s)

    def snapshot(self) -> Dict[str, Any]:
        out = self.stats.snapshot()
        out["params_step"] = self.engine.params_step
        if self.scheduler is not None:
            out["cb"] = self.scheduler.snapshot()
        return out


def _make_handler(server: InferenceServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet: stats, not stdout
            pass

        def _reply(self, code: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code: int, text: str,
                        ctype: str = "text/plain; version=0.0.4; "
                                     "charset=utf-8") -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/stats":
                self._reply(200, server.snapshot())
            elif self.path == "/metrics":
                self._reply_text(200, server.metrics.render_prometheus())
            elif self.path == "/healthz":
                h = server.engine.health()
                # transport negotiation: a healthy worker advertises
                # its binary listener here; clients that never look
                # stay on HTTP (the always-on debug surface)
                wa = server.wire_address
                if wa is not None:
                    h["wire_port"] = wa[1]
                self._reply(200 if h["ok"] else 503, h)
            elif self.path == "/trace":
                # this worker's span ring (Perfetto dict, carrying
                # wall_origin_s + process tags) — what obs/collect.py
                # pulls to merge the fleet's buffers into one timeline
                self._reply(200, obs.trace_dump())
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def _remote_trace(self):
            """The caller's trace context from the header pair, or
            None — the anchor that makes this process's spans children
            of the router's dispatch span after the merge."""
            return qos.trace_from_headers(
                self.headers.get(qos.TRACE_HEADER),
                self.headers.get(qos.PARENT_SPAN_HEADER))

        def do_POST(self):
            mode = self.path.lstrip("/")
            # trace context rides every POST: the span this handler
            # opens is anchored under the caller's parent span id, so
            # the merged fleet trace shows router dispatch -> worker
            # admission as one tree (qos.trace_from_headers never
            # rejects a request over a malformed telemetry header)
            link = self._remote_trace()
            tr = link[0] if link else None
            psid = (link[1] or None) if link else None
            if self.path == "/admin/reload":
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    step = req.get("step")
                    with obs.span("serve.reload", trace=tr,
                                  parent=psid, step=step):
                        outcome = server.engine.reload_to(
                            None if step is None else int(step))
                    self._reply(200, {
                        "outcome": outcome,
                        "step": server.engine.params_step})
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                return
            if mode not in ("generate", "predict"):
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                tokens = np.asarray(req["tokens"], np.int32)
                timeout = req.get("timeout")
                # end-to-end deadline: remaining-ms header re-anchored
                # onto THIS process's monotonic clock (serve/qos.py)
                deadline = qos.deadline_from_header(
                    self.headers.get(qos.DEADLINE_HEADER))
                priority = qos.check_priority(
                    req.get("priority")
                    or self.headers.get(qos.PRIORITY_HEADER))
                # degrade-never-reject: a missing/garbled tenant id
                # folds to "default" (check_tenant cannot raise)
                tenant = qos.check_tenant(
                    req.get("tenant")
                    or self.headers.get(qos.TENANT_HEADER))
                with obs.span("serve.request", trace=tr, parent=psid,
                              mode=mode, priority=priority,
                              tenant=tenant):
                    if mode == "generate":
                        max_new = req.get("max_new")
                        if max_new is not None:
                            max_new = int(max_new)
                        if req.get("stream") and \
                                server.scheduler is not None:
                            self._stream_generate(
                                tokens, timeout, max_new, deadline,
                                priority, tenant=tenant,
                                resume_from=int(
                                    req.get("resume_from", 0)))
                            return
                        out = server.generate(tokens, timeout=timeout,
                                              max_new=max_new,
                                              deadline=deadline,
                                              priority=priority,
                                              tenant=tenant)
                    else:
                        out = server.predict(tokens, timeout=timeout,
                                             deadline=deadline,
                                             priority=priority,
                                             tenant=tenant)
                self._reply(200, out)
            except Overloaded as e:
                self._reply(503, {"error": str(e),
                                  "retry_after": e.retry_after},
                            {"Retry-After": f"{e.retry_after:.3f}"})
            except (DeadlineExpired, TimeoutError) as e:
                self._reply(504, {"error": str(e)})
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
            except Exception as e:  # noqa: BLE001 — failed batch etc.
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):X}\r\n".encode()
                             + data + b"\r\n")

        def _stream_generate(self, tokens, timeout, max_new,
                             deadline=None, priority="interactive",
                             tenant=None, resume_from=0) -> None:
            """Chunked-transfer ndjson: one {"token": t, "i": n} line
            per produced token as the slot produces it (n the absolute
            sequence number — resume_from-based for a failover
            re-admission; old clients simply ignore the extra key),
            then a final {"done": true, ...} summary line.  Admission
            errors — including an inadmissible resume_from — raise
            BEFORE any byte is sent and take the normal status-code
            path in do_POST; a mid-stream failure becomes a terminal
            {"error": ...} line (the 200 is already on the wire).

            Lines are flushed in batches under the spec's
            flush_tokens/flush_ms knobs (one chunked write carrying
            several ndjson lines) — except the FIRST token of the
            stream, which always flushes alone so first-token latency
            never pays for batching."""
            t0 = time.monotonic()
            ticket = server.scheduler.submit(tokens, timeout=timeout,
                                             max_new=max_new,
                                             deadline=deadline,
                                             priority=priority,
                                             tenant=tenant,
                                             resume_from=resume_from)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            spec = server.engine.spec
            co = wire.LineCoalescer(
                self._chunk,
                flush_tokens=getattr(spec, "flush_tokens", 8),
                flush_ms=getattr(spec, "flush_ms", 4.0))
            i = ticket.first_index
            budget = server._wait_budget(timeout, deadline)
            first = True
            try:
                done = False
                while not done:
                    evs = ticket.drain_events(
                        max_n=1 if first else co.flush_tokens,
                        timeout=budget,
                        linger_s=0.0 if first else co.flush_s)
                    first = False
                    for kind, payload in evs:
                        if kind == "tok":
                            line = {"token": payload, "i": i}
                            i += 1
                            co.add(wire.timed_json_dumps(line)
                                   + b"\n")
                        elif kind == "failed":
                            # tokens drained before the failure are
                            # already queued; flush them, then the
                            # error line below
                            raise payload
                        else:
                            line = dict(payload)
                            line["done"] = True
                            line["latency_ms"] = round(
                                (time.monotonic() - t0) * 1e3, 3)
                            co.add(wire.timed_json_dumps(line)
                                   + b"\n", urgent=True)
                            done = True
            except Exception as e:  # noqa: BLE001 — mid-stream failure
                co.add(json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode()
                    + b"\n", urgent=True)
            self._chunk(b"")      # terminal 0-length chunk

    return Handler
