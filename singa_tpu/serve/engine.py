"""Inference engine: compiled-per-bucket decode programs over the
latest *healthy* checkpoint, with atomic hot-reload.

The serving hot path must never trace ("RPC Considered Harmful" — keep
per-request overhead off the device path): the engine AOT-compiles one
generate and/or predict executable per (batch, prompt_len) shape
bucket (`jax.jit(...).lower(...).compile()`), and thereafter only ever
invokes Compiled executables — a hard guarantee of zero recompiles,
made observable through `ServeStats.compiles` (incremented ONLY inside
`_compile`, so a warmed server must hold the counter constant).

Variable-length prompts are LEFT-padded to the bucket length with a
per-key validity mask (see `_attn_cached`'s `kmask`): RoPE rotations
are relative, so left-padding preserves every attended (query, key)
distance, the last real prompt token sits at a uniform position P-1
across the batch, and masked pad keys contribute exactly zero after
softmax.  Padded batched decode therefore matches unpadded decode
bit-for-bit in f32.

Hot reload (`poll_reload`) is cheap-poll + atomic-swap: compare
`CheckpointManager.fingerprint()` (two stats, no reads); on change,
`restore(skip_unhealthy=True)` walks back past numerically suspect
snapshots, the new params are placed on device and swapped in with a
single attribute assignment.  Dispatchers read `engine.params` once
per micro-batch, so in-flight batches finish on the params they
started with — a reload never drops a request.  Every degradation is
a counted non-event: a failed restore keeps the old params live
(`reload_failures`, fingerprint unchanged so the next poll retries);
a walk-back that lands on the already-served step is `reloads_refused`
(fingerprint recorded so it is not re-attempted every poll); a poll
that races a LIVE writer (a step list or MANIFEST.json caught
mid-rename/half-written) is `torn_polls` — surfaced as "no change",
never an exception and never a reload off the torn read, so a trainer
publishing into the served workspace is safe by construction
(docs/PIPELINE.md).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import perf
from ..models.generate import (_sample, forward_cached, forward_paged,
                               init_cache, scatter_prefill)
from ..utils import faults
from ..utils.checkpoint import CheckpointManager
from .kvcache import init_pools
from .stats import ServeStats

MODES = ("generate", "predict")


@dataclass(frozen=True)
class ServeSpec:
    """Serving configuration.  `buckets` is the closed set of compiled
    (batch, prompt_len) shapes — every request is padded into one of
    them, so after `warmup()` no program is ever compiled again.
    `bucket_for` picks the smallest admissible bucket: fewest padded
    slots first, then shortest prompt padding."""
    buckets: Tuple[Tuple[int, int], ...] = ((1, 16), (4, 16), (8, 32))
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    queue_capacity: int = 64
    batch_window_s: float = 0.01
    request_timeout_s: float = 5.0
    reload_poll_s: float = 1.0
    degraded_after: int = 3   # consecutive failed batches -> degraded
    seed: int = 0
    # engine.stall fault site: the host-side sleep the silent "stall"
    # kind latches onto this engine's every compiled call — the
    # deterministic straggler for the hedging bench
    stall_fault_s: float = 0.25
    # priority-aware brownout (serve/qos.py): under queue pressure
    # admission sheds lowest class first.  best_effort is shed once the
    # queue is `brownout_be_frac` full, batch at `brownout_batch_frac`;
    # interactive sheds only when the queue is actually full
    brownout_be_frac: float = 0.5
    brownout_batch_frac: float = 0.75
    # continuous batching (serve/scheduler.py): cb=on replaces the
    # static generate buckets with a paged-KV slot scheduler.  The
    # compiled geometry is (cb_slots, blocks-per-slot, cb_block_len,
    # pool size) ONLY — exactly two programs (prefill + decode step)
    # regardless of traffic mix, so the zero-recompile guarantee holds
    cb: str = "off"           # "on" | "off"
    cb_slots: int = 8         # concurrent decode slots (S)
    cb_block_len: int = 16    # tokens per KV block
    cb_blocks: int = 0        # pool size incl. null block; 0 = auto
    cb_prompt_cap: int = 0    # longest admissible prompt; 0 = widest
                              # bucket prompt_len
    # model family this engine serves: half of the (family, step)
    # serving fingerprint.  Engines advertise it on /healthz, the
    # router dispatches a request's `model` onto matching members
    # only, and a failover resume must match BOTH halves.  Parsed
    # lowercase by the str branch of `parse`
    family: str = "default"
    # token flush batching (serve/wire.py): streamed tokens go out in
    # frames/chunks of up to `flush_tokens`, lingering `flush_ms` for
    # stragglers — on both the binary and HTTP ndjson surfaces.  The
    # first token of a stream always flushes alone (first-token
    # latency is a gated stage).  flush_tokens=1 disables batching
    flush_tokens: int = 8
    flush_ms: float = 4.0

    def __post_init__(self):
        norm = []
        for b in self.buckets:
            bb, pp = int(b[0]), int(b[1])
            if bb < 1 or pp < 1:
                raise ValueError(f"bad bucket {b!r}: batch and "
                                 f"prompt_len must be >= 1")
            norm.append((bb, pp))
        if not norm:
            raise ValueError("ServeSpec needs at least one bucket")
        object.__setattr__(self, "buckets",
                           tuple(sorted(set(norm),
                                        key=lambda c: (c[1], c[0]))))
        if int(self.max_new_tokens) < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{self.max_new_tokens}")
        if int(self.queue_capacity) < 1:
            raise ValueError(f"queue_capacity must be >= 1, got "
                             f"{self.queue_capacity}")
        if int(self.degraded_after) < 1:
            raise ValueError(f"degraded_after must be >= 1, got "
                             f"{self.degraded_after}")
        if self.cb not in ("on", "off"):
            raise ValueError(f"cb must be 'on' or 'off', got "
                             f"{self.cb!r}")
        if int(self.cb_slots) < 1 or int(self.cb_block_len) < 1:
            raise ValueError("cb_slots and cb_block_len must be >= 1")
        if int(self.cb_blocks) < 0 or int(self.cb_prompt_cap) < 0:
            raise ValueError("cb_blocks and cb_prompt_cap must be "
                             ">= 0 (0 = auto)")
        if float(self.stall_fault_s) < 0:
            raise ValueError(f"stall_fault_s must be >= 0, got "
                             f"{self.stall_fault_s}")
        be, ba = (float(self.brownout_be_frac),
                  float(self.brownout_batch_frac))
        if not (0 < be <= ba <= 1):
            raise ValueError(
                f"brownout fractions must satisfy 0 < be_frac <= "
                f"batch_frac <= 1, got be={be} batch={ba}")
        fam = str(self.family).strip().lower()
        if not fam:
            raise ValueError("family must be a non-empty name")
        object.__setattr__(self, "family", fam)
        if int(self.flush_tokens) < 1:
            raise ValueError(f"flush_tokens must be >= 1, got "
                             f"{self.flush_tokens}")
        if float(self.flush_ms) < 0:
            raise ValueError(f"flush_ms must be >= 0, got "
                             f"{self.flush_ms}")

    @property
    def max_prompt_len(self) -> int:
        return max(p for _, p in self.buckets)

    # -- continuous-batching geometry (all derived, all static) -------------
    @property
    def cb_on(self) -> bool:
        return self.cb == "on"

    @property
    def cb_prefill_len(self) -> int:
        """Compiled prefill width P: the prompt cap rounded UP to a
        block multiple (prefill scatters whole blocks)."""
        cap = int(self.cb_prompt_cap) or self.max_prompt_len
        bl = int(self.cb_block_len)
        return -(-cap // bl) * bl

    @property
    def cb_max_prompt_len(self) -> int:
        """Longest admissible prompt under cb (fail-fast bound)."""
        return int(self.cb_prompt_cap) or self.max_prompt_len

    @property
    def cb_blocks_per_slot(self) -> int:
        """Table width T: worst-case blocks one slot can ever hold
        (full prefill + a full generation)."""
        bl = int(self.cb_block_len)
        return -(-(self.cb_prefill_len + int(self.max_new_tokens)) // bl)

    @property
    def cb_pool_blocks(self) -> int:
        """Pool size incl. the null block.  Auto (cb_blocks=0) sizes
        for every slot at worst case — exhaustion then needs an
        explicit smaller cb_blocks (the shed tests use one)."""
        n = int(self.cb_blocks)
        if n == 0:
            n = int(self.cb_slots) * self.cb_blocks_per_slot + 1
        return n

    @property
    def max_batch(self) -> int:
        return max(b for b, _ in self.buckets)

    def bucket_for(self, n: int, prompt_len: int) -> Tuple[int, int]:
        """Smallest admissible bucket for `n` requests whose longest
        prompt is `prompt_len`.  When no bucket holds all `n`, the
        widest admissible one is returned (the caller dispatches a full
        batch and re-queues the overflow)."""
        cands = [c for c in self.buckets if c[1] >= prompt_len]
        if not cands:
            raise ValueError(
                f"prompt_len={prompt_len} exceeds every bucket "
                f"{self.buckets}; admission should have rejected it")
        fit = [c for c in cands if c[0] >= n]
        if fit:
            return min(fit, key=lambda c: (c[0], c[1]))
        return min(cands, key=lambda c: (-c[0], c[1]))

    @classmethod
    def parse(cls, spec: str) -> "ServeSpec":
        """CLI grammar (HealthSpec mold): comma/semicolon-separated
        `key=value`.  Buckets are `/`-separated BxP entries, e.g.
        `"buckets=1x8/4x16,max_new_tokens=8,eos_id=2"`.  `eos_id=none`
        clears the eos."""
        kw: Dict[str, Any] = {}
        types = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key, _, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if key not in types:
                    raise ValueError(f"unknown key {key!r}")
                if key == "buckets":
                    kw[key] = tuple(
                        tuple(int(x) for x in item.lower().split("x"))
                        for item in val.split("/") if item)
                elif key == "eos_id":
                    kw[key] = None if val.lower() in ("none", "") \
                        else int(val)
                elif "str" in str(types[key]):
                    kw[key] = val.lower()
                elif "float" in str(types[key]):
                    kw[key] = float(val)
                else:
                    kw[key] = int(val)
            except ValueError as e:
                raise ValueError(f"bad serve spec entry {part!r} "
                                 f"(want key=value): {e}") from e
        return cls(**kw)


def _left_pad_mask(prompt_len: int, max_len: int,
                   plens: jnp.ndarray) -> jnp.ndarray:
    """(B, max_len) bool: key position j of row i is attendable iff
    j >= prompt_len - plens[i].  Prompt tokens occupy the RIGHT end of
    the padded prompt region; every generated position (>= prompt_len)
    is attendable for all rows."""
    kpos = jnp.arange(max_len)[None, :]
    return kpos >= (prompt_len - plens)[:, None]


def _tree_spec(tree):
    return jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), str(jnp.asarray(a).dtype)), tree)


class InferenceEngine:
    """Loads params from the latest healthy checkpoint, compiles one
    executable per (mode, batch, prompt_len) bucket, runs padded
    micro-batches, and hot-reloads checkpoints without dropping
    in-flight work.  See the module docstring for the swap/degrade
    contract.  Thread-safe: `_compile` is serialized; `run_batch`
    callers pass the params they captured."""

    def __init__(self, net, spec: ServeSpec,
                 workspace: Optional[str] = None,
                 params: Optional[Dict[str, Any]] = None,
                 stats: Optional[ServeStats] = None, log_fn=print,
                 pinned: bool = False):
        if workspace is None and params is None:
            raise ValueError("InferenceEngine needs a checkpoint "
                             "workspace or explicit params")
        self.net = net
        self.spec = spec
        self.stats = stats if stats is not None else ServeStats()
        self.log = log_fn
        self.ckpt = (CheckpointManager(workspace, log_fn=log_fn)
                     if workspace is not None else None)
        self._params = (jax.device_put(params)
                        if params is not None else None)
        # the fresh-init fallback, kept forever: `reload_to(step=-1)`
        # restores it, so a fleet rollback works even when the pinned
        # step is -1 (cold start — nothing was ever promoted, yet a
        # canaried-then-rejected first checkpoint must still be
        # unseated from the canary)
        self._init_params = self._params
        self.params_step: int = -1
        self._fingerprint: Optional[tuple] = None
        # pinned-fingerprint mode (fleet members): the engine never
        # follows the workspace on its own — poll_reload is a no-op
        # and only an explicit reload_to (the rollout controller's
        # command channel) moves the served params
        self.pinned = bool(pinned)
        # honest /healthz: a refused/failed reload leaves the engine
        # serving STALE params; recorded here (and cleared by the next
        # successful reload) so the router sees a degraded verdict
        # instead of an unconditional ok
        self._stale_reason: Optional[str] = None
        # the params served immediately before the last EXPLICIT
        # reload (the fleet rollout's command channel).  The pinned
        # snapshot on disk can be GC'd (max_to_keep) while the fleet
        # still serves it, so a canary rollback to the pinned step
        # must be satisfiable from memory — one extra params copy per
        # fleet engine is the price of an instant, disk-independent
        # rollback.  Solo (polling) engines never populate it.
        self._prev_params = None
        self._prev_step: Optional[int] = None
        self._compiled: Dict[Tuple[str, int, int], Any] = {}
        self._compile_lock = threading.Lock()
        # CompileWatch scope: per-engine, so a fleet member (or a
        # fresh autoscaled engine) warming up after its siblings never
        # reads as a recompile anomaly — only a compile AFTER this
        # engine's own warmup() trips the invariant
        self._perf_scope = f"engine-{id(self):x}"
        self._key_counter = 0
        self._key_lock = threading.Lock()
        # injected straggler latency (engine.stall / set_stall): a
        # host-side sleep before every compiled call.  The engine stays
        # healthy — probes pass, requests complete — it is just SLOW,
        # which is exactly the failure mode hedging exists for.
        self.stall_s = 0.0
        # reload-poll supervision (server._poll_loop): consecutive
        # unexpected poll deaths — /healthz degrades once the streak
        # crosses degraded_after, because an engine whose poller
        # cannot stay alive is quietly going stale
        self._poll_death_streak = 0

    def note_poll_death(self) -> int:
        self._poll_death_streak += 1
        return self._poll_death_streak

    def note_poll_ok(self) -> None:
        self._poll_death_streak = 0

    # -- params lifecycle ---------------------------------------------------
    @property
    def params(self):
        """The live params tree.  Read ONCE per micro-batch and pass to
        `run_batch` — that single read is what makes the hot-reload
        swap atomic with respect to in-flight work."""
        return self._params

    def _swap(self, params, step: int) -> None:
        new = jax.device_put(params)
        if self._params is not None and \
                _tree_spec(new) != _tree_spec(self._params):
            raise RuntimeError(
                f"checkpoint step {step} has a different parameter "
                f"geometry than the serving model; refusing the swap")
        self._params = new            # atomic: one attribute store
        self.params_step = step
        perf.set_memory_tree("serve_params", new,
                             scope=self._perf_scope)

    def load(self) -> int:
        """Initial load: latest healthy checkpoint (walks back past
        unhealthy/corrupt snapshots).  Falls back to constructor params
        when the workspace has nothing restorable.  Returns the served
        step (-1 = constructor params)."""
        if self.ckpt is not None:
            restored = self.ckpt.restore(skip_unhealthy=True)
            self._fingerprint = self.ckpt.fingerprint()
            if restored is not None:
                p, _, step = restored
                self._swap(p, step)
            elif self._params is None:
                raise RuntimeError(
                    f"no restorable healthy checkpoint under "
                    f"{self.ckpt.dir} and no fallback params")
        if self._params is not None:   # constructor-params path never
            perf.set_memory_tree(      # went through _swap
                "serve_params", self._params, scope=self._perf_scope)
        return self.params_step

    def poll_reload(self) -> str:
        """One hot-reload attempt; returns "reloaded" | "unchanged" |
        "refused" | "failed".  Never raises and never unseats the live
        params on failure — the degrade contract the server's poll
        thread relies on (the process stays up, old params keep
        serving)."""
        if self.ckpt is None:
            return "unchanged"
        if self.pinned:
            # fleet member: the rollout controller owns reloads
            return "pinned"
        with obs.span("engine.reload") as sp:
            outcome = self._poll_reload()
            sp.set(outcome=outcome, step=self.params_step)
        if outcome != "unchanged":
            obs.emit_event("serve.reload", outcome=outcome,
                           step=self.params_step)
        return outcome

    def _poll_reload(self) -> str:
        try:
            faults.maybe_fault("serve.reload")
            torn_before = self.ckpt.torn_polls
            fp = self.ckpt.fingerprint()
            if self.ckpt.torn_polls > torn_before:
                # the poll raced a live writer (mid-rename / partial
                # MANIFEST.json): a counted non-event, NOT a failure —
                # fingerprint returned the previous token, so the next
                # tick simply retries once the write completes.  Never
                # reload off a torn read.
                self.stats.count("torn_polls")
                return "unchanged"
            if fp == self._fingerprint:
                return "unchanged"
            restored = self.ckpt.restore(skip_unhealthy=True)
            if restored is None or restored[2] == self.params_step:
                # nothing newer that is healthy (the walk-back landed on
                # what we already serve, or on nothing).  Record the
                # fingerprint so the refusal is not re-litigated every
                # poll tick; a future save changes it again.
                self._fingerprint = fp
                self.stats.count("reloads_refused")
                self._stale_reason = (
                    f"reload refused: newer checkpoint on disk is not "
                    f"healthy/restorable; serving stale step "
                    f"{self.params_step}")
                self.log("serve: reload refused — no newer healthy "
                         f"checkpoint (serving step {self.params_step})")
                return "refused"
            p, _, step = restored
            self._swap(p, step)
            self._fingerprint = fp
            self._stale_reason = None
            self.stats.count("reloads")
            self.log(f"serve: hot-reloaded checkpoint step {step}")
            return "reloaded"
        except Exception as e:  # noqa: BLE001 — degrade, never crash
            # fingerprint deliberately NOT updated: the next poll
            # retries the same reload instead of wedging on old params
            self.stats.count("reload_failures")
            self._stale_reason = (
                f"reload failed ({type(e).__name__}); serving stale "
                f"step {self.params_step}")
            self.log(f"warning: serve reload failed "
                     f"({type(e).__name__}: {e}); keeping params from "
                     f"step {self.params_step}")
            return "failed"

    def reload_to(self, step: Optional[int] = None,
                  skip_unhealthy: bool = False) -> str:
        """Explicit reload — the fleet rollout controller's command
        channel (works on a pinned engine; that is its point).  Loads
        checkpoint `step` (None = latest on disk), by default WITHOUT
        the healthy-verdict walk-back: a canary deliberately serves
        the exact target snapshot and the rollout verdict — not the
        manifest alone — decides its fate.  `restore` still walks back
        past a torn/corrupt target, so the caller must verify
        `params_step` landed where it asked.  Returns "reloaded" |
        "unchanged" | "refused" | "failed"; never raises and never
        unseats the live params on failure."""
        if self.ckpt is None:
            return "refused"
        with obs.span("engine.reload", target=step) as sp:
            outcome = self._reload_to(step, skip_unhealthy)
            sp.set(outcome=outcome, step=self.params_step)
        if outcome != "unchanged":
            obs.emit_event("serve.reload", outcome=outcome,
                           step=self.params_step, target=step)
        return outcome

    def _reload_to(self, step: Optional[int],
                   skip_unhealthy: bool) -> str:
        try:
            faults.maybe_fault("serve.reload")
            if step is not None and int(step) < 0:
                # rollback target "-1": the fresh-init fallback params
                # (cold-start fleets pin there before any promotion)
                if self._init_params is None:
                    self.stats.count("reloads_refused")
                    self.log("serve: reload to step -1 refused — no "
                             "fresh-init fallback params")
                    return "refused"
                if self.params_step < 0:
                    self._stale_reason = None
                    return "unchanged"
                self._prev_params = self._params
                self._prev_step = self.params_step
                self._params = self._init_params
                self.params_step = -1
                self._stale_reason = None
                self.stats.count("reloads")
                self.log("serve: reloaded to fresh-init params "
                         "(step -1)")
                return "reloaded"
            if step is not None and int(step) == self.params_step:
                # already serving the requested step — e.g. restoring
                # a refused canary to a pinned step the checkpoint GC
                # has since deleted.  The params are live in memory, so
                # touching disk could only fail; by definition the
                # engine is not stale either.
                self._stale_reason = None
                return "unchanged"
            fp = self.ckpt.fingerprint()
            restored = self.ckpt.restore(step=step,
                                         skip_unhealthy=skip_unhealthy)
            if restored is None:
                if (step is not None and self._prev_params is not None
                        and int(step) == self._prev_step):
                    # the requested snapshot was GC'd off disk
                    # (max_to_keep) but it is what this engine served
                    # immediately before the current params — a canary
                    # being restored to the pinned step.  Swap back
                    # from memory; disk owes us nothing.
                    prev_p, prev_s = self._prev_params, self._prev_step
                    self._prev_params = self._params
                    self._prev_step = self.params_step
                    self._params = prev_p
                    self.params_step = prev_s
                    self._fingerprint = fp
                    self._stale_reason = None
                    self.stats.count("reloads")
                    self.log(f"serve: reloaded to step {step} from "
                             f"in-memory previous params (snapshot no "
                             f"longer on disk)")
                    return "reloaded"
                self.stats.count("reloads_refused")
                self._stale_reason = (
                    f"explicit reload to step {step} found nothing "
                    f"restorable; serving stale step {self.params_step}")
                self.log(f"serve: explicit reload to step {step} "
                         f"refused — nothing restorable")
                return "refused"
            p, _, got = restored
            if got == self.params_step:
                # already serving it (e.g. a rollback to the pinned
                # step that never left it) — success, not a refusal
                self._fingerprint = fp
                self._stale_reason = None
                return "unchanged"
            self._prev_params = self._params
            self._prev_step = self.params_step
            self._swap(p, got)
            self._fingerprint = fp
            self._stale_reason = None
            self.stats.count("reloads")
            self.log(f"serve: reloaded to checkpoint step {got}"
                     + (f" (asked for {step})"
                        if step is not None and got != step else ""))
            return "reloaded"
        except Exception as e:  # noqa: BLE001 — degrade, never crash
            self.stats.count("reload_failures")
            self._stale_reason = (
                f"reload to step {step} failed ({type(e).__name__}); "
                f"serving stale step {self.params_step}")
            self.log(f"warning: explicit reload to step {step} failed "
                     f"({type(e).__name__}: {e}); keeping params from "
                     f"step {self.params_step}")
            return "failed"

    # -- health -------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Honest liveness verdict for /healthz and the fleet router.
        Degrades (ok=False) when the engine is *wedged* — `spec.
        degraded_after` consecutive failed batches — or *stale* — a
        refused/failed reload left it serving params older than what
        the workspace holds.  A healthy report is earned, not
        unconditional."""
        reasons = []
        k = int(self.spec.degraded_after)
        streak = self.stats.consecutive_batch_failures
        if streak >= k:
            reasons.append(f"{streak} consecutive failed batches "
                           f"(threshold {k})")
        if self._stale_reason is not None:
            reasons.append(self._stale_reason)
        if self._poll_death_streak >= k:
            reasons.append(
                f"reload poll died {self._poll_death_streak} times "
                f"in a row (threshold {k}); params may be going "
                f"stale")
        return {"ok": not reasons,
                "status": "ok" if not reasons else "degraded",
                "step": self.params_step,
                "family": self.spec.family,
                "pinned": self.pinned,
                "reasons": reasons}

    # -- compiled programs --------------------------------------------------
    def _build_generate(self, batch: int, prompt_len: int):
        net, spec = self.net, self.spec
        max_new = int(spec.max_new_tokens)
        max_len = prompt_len + max_new
        temperature, top_k, top_p = (float(spec.temperature),
                                     int(spec.top_k), float(spec.top_p))
        eos_id = spec.eos_id

        def fn(params, tokens, plens, key):
            dtype = jax.tree_util.tree_leaves(params)[0].dtype
            cache = init_cache(net, batch, max_len, dtype)
            kmask = _left_pad_mask(prompt_len, max_len, plens)
            logits, cache = forward_cached(net, params, tokens, cache,
                                           0, kmask=kmask)
            keys = jax.random.split(key, max_new)
            tok0 = _sample(logits[:, -1], keys[0], temperature, top_k,
                           top_p)
            done0 = (jnp.zeros((batch,), jnp.bool_) if eos_id is None
                     else tok0 == eos_id)

            def step(carry, k):
                tok, cache, pos, done = carry
                lg, cache = forward_cached(net, params, tok[:, None],
                                           cache, pos, kmask=kmask)
                nxt = _sample(lg[:, -1], k, temperature, top_k, top_p)
                if eos_id is not None:
                    nxt = jnp.where(done, eos_id, nxt)
                    done = done | (nxt == eos_id)
                return (nxt, cache, pos + 1, done), nxt

            (_, _, _, _), rest = jax.lax.scan(
                step, (tok0, cache, jnp.int32(prompt_len), done0),
                keys[1:])
            return jnp.concatenate([tok0[:, None], rest.T], axis=1)

        return fn

    def _build_predict(self, batch: int, prompt_len: int):
        net, spec = self.net, self.spec
        max_len = prompt_len + 1

        def fn(params, tokens, plens):
            dtype = jax.tree_util.tree_leaves(params)[0].dtype
            cache = init_cache(net, batch, max_len, dtype)
            kmask = _left_pad_mask(prompt_len, max_len, plens)
            logits, _ = forward_cached(net, params, tokens, cache, 0,
                                       kmask=kmask)
            # left-padding puts every row's last real token at P-1, so
            # one static slice reads the next-token distribution
            return jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1)

        return fn

    # -- continuous-batching programs ---------------------------------------
    def _build_cb_prefill(self):
        """ONE compiled prefill at fixed (1, P): the prompt is
        RIGHT-padded to P (the causal mask alone keeps pad keys out of
        every real query's horizon; pad K/V garbage lands in reserved
        or null blocks and is masked/overwritten downstream), runs
        through the ordinary contiguous `forward_cached`, samples the
        first token from the last REAL position, and scatters the
        contiguous cache into the slot's pool blocks."""
        net, spec = self.net, self.spec
        p_len = spec.cb_prefill_len
        temperature, top_k, top_p = (float(spec.temperature),
                                     int(spec.top_k), float(spec.top_p))

        def fn(params, pools, tokens, plen, row, key):
            dtype = jax.tree_util.tree_leaves(params)[0].dtype
            cache = init_cache(net, 1, p_len, dtype)
            logits, cache = forward_cached(net, params, tokens, cache, 0)
            last = jax.lax.dynamic_index_in_dim(logits[0], plen - 1,
                                                axis=0, keepdims=True)
            tok0 = _sample(last, key, temperature, top_k, top_p)[0]
            return tok0, scatter_prefill(pools, cache, row)

        return fn

    def _build_cb_decode(self):
        """ONE compiled decode step at fixed slot count S: every
        active slot advances one token against its paged blocks
        (forward_paged), one `_sample` call produces all S next
        tokens.  Join/retire is pure host bookkeeping in the
        scheduler — the program never changes shape."""
        net, spec = self.net, self.spec
        temperature, top_k, top_p = (float(spec.temperature),
                                     int(spec.top_k), float(spec.top_p))

        def fn(params, pools, tokens, ntoks, tables, key):
            logits, pools = forward_paged(net, params, tokens[None],
                                          pools, tables, ntoks)
            nxt = _sample(logits[0], key, temperature, top_k, top_p)
            return nxt, pools

        return fn

    def _pools_spec(self):
        dtype = jax.tree_util.tree_leaves(self._params)[0].dtype
        pools = init_pools(self.net, self.spec.cb_pool_blocks,
                           self.spec.cb_block_len, dtype)
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), pools)

    def _compile_cb(self, which: str):
        """AOT-compile the cb prefill or decode program (same lock,
        same `compiles` accounting as `_compile` — the counter still
        moves ONLY inside the two compile paths).  Pools are donated:
        the scheduler threads the returned pools into the next call,
        so the pool never exists twice on device."""
        spec = self.spec
        key = (f"cb_{which}", spec.cb_slots, spec.cb_blocks_per_slot)
        got = self._compiled.get(key)
        if got is not None:
            perf.lookup_hit(key[0])
            return got
        with self._compile_lock:
            got = self._compiled.get(key)
            if got is not None:
                perf.lookup_hit(key[0])
                return got
            if self._params is None:
                raise RuntimeError("engine has no params; call load()")
            geometry = (f"slots={spec.cb_slots},"
                        f"blocks={spec.cb_pool_blocks},"
                        f"block_len={spec.cb_block_len}")
            with obs.span("engine.compile", mode=f"cb_{which}",
                          slots=spec.cb_slots,
                          blocks=spec.cb_pool_blocks), \
                 perf.compile_span(key[0], geometry=geometry,
                                   scope=self._perf_scope,
                                   family="generate"):
                p_spec = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    self._params)
                pools = self._pools_spec()
                rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
                if which == "prefill":
                    fn = self._build_cb_prefill()
                    tok = jax.ShapeDtypeStruct(
                        (1, spec.cb_prefill_len), jnp.int32)
                    plen = jax.ShapeDtypeStruct((), jnp.int32)
                    row = jax.ShapeDtypeStruct(
                        (spec.cb_prefill_len // spec.cb_block_len,),
                        jnp.int32)
                    compiled = jax.jit(fn, donate_argnums=(1,)).lower(
                        p_spec, pools, tok, plen, row, rng).compile()
                elif which == "decode":
                    fn = self._build_cb_decode()
                    s = spec.cb_slots
                    tok = jax.ShapeDtypeStruct((s,), jnp.int32)
                    ntoks = jax.ShapeDtypeStruct((s,), jnp.int32)
                    tables = jax.ShapeDtypeStruct(
                        (s, spec.cb_blocks_per_slot), jnp.int32)
                    compiled = jax.jit(fn, donate_argnums=(1,)).lower(
                        p_spec, pools, tok, ntoks, tables, rng).compile()
                else:
                    raise ValueError(f"unknown cb program {which!r}")
            self.stats.count("compiles")
            self._compiled[key] = compiled
            perf.harvest(key[0], compiled)
            # analytic MemoryWatch component: the pool spec carries
            # the exact shapes init_pools allocates
            perf.set_memory_tree("kv_pool", pools,
                                 scope=self._perf_scope)
            return compiled

    def run_cb_prefill(self, params, pools, tokens: np.ndarray,
                       plen: int, row: np.ndarray):
        """One slot prefill: `tokens` (1, P) int32 RIGHT-padded,
        `row` the first P//block_len entries of the slot's block
        table.  Returns (first sampled token (int), new pools) —
        `pools` was donated; callers must use the returned tree."""
        self._maybe_stall()
        compiled = self._compile_cb("prefill")
        t0 = time.perf_counter()
        tok0, pools = compiled(params, pools,
                               jnp.asarray(tokens, jnp.int32),
                               jnp.int32(plen),
                               jnp.asarray(row, jnp.int32),
                               self._next_key())
        tok0 = int(tok0)
        perf.observe_step("cb_prefill", time.perf_counter() - t0)
        perf.mark_serving_ready()      # first warm token (latch)
        return tok0, pools

    def run_cb_decode(self, params, pools, tokens: np.ndarray,
                      ntoks: np.ndarray, tables: np.ndarray):
        """One decode step for all S slots.  Returns ((S,) int32 next
        tokens on host, new pools).  `pools` was donated."""
        self._maybe_stall()
        compiled = self._compile_cb("decode")
        t0 = time.perf_counter()
        nxt, pools = compiled(params, pools,
                              jnp.asarray(tokens, jnp.int32),
                              jnp.asarray(ntoks, jnp.int32),
                              jnp.asarray(tables, jnp.int32),
                              self._next_key())
        nxt = np.asarray(nxt)
        perf.observe_step("cb_decode", time.perf_counter() - t0)
        return nxt, pools

    def _compile(self, mode: str, batch: int, prompt_len: int):
        key = (mode, batch, prompt_len)
        got = self._compiled.get(key)
        if got is not None:
            perf.lookup_hit(mode)
            return got
        with self._compile_lock:
            got = self._compiled.get(key)
            if got is not None:
                perf.lookup_hit(mode)
                return got
            if self._params is None:
                raise RuntimeError("engine has no params; call load()")
            if mode not in MODES:
                raise ValueError(f"unknown mode {mode!r}; modes are "
                                 f"{MODES}")
            with obs.span("engine.compile", mode=mode, batch=batch,
                          plen=prompt_len), \
                 perf.compile_span(mode,
                                   geometry=f"b{batch}_p{prompt_len}",
                                   scope=self._perf_scope,
                                   family=mode):
                p_spec = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    self._params)
                tok = jax.ShapeDtypeStruct((batch, prompt_len),
                                           jnp.int32)
                pl = jax.ShapeDtypeStruct((batch,), jnp.int32)
                if mode == "generate":
                    fn = self._build_generate(batch, prompt_len)
                    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
                    compiled = jax.jit(fn).lower(p_spec, tok, pl,
                                                 rng).compile()
                else:
                    fn = self._build_predict(batch, prompt_len)
                    compiled = jax.jit(fn).lower(p_spec, tok,
                                                 pl).compile()
            self.stats.count("compiles")
            self._compiled[key] = compiled
            perf.harvest(mode, compiled)
            return compiled

    def warmup(self, modes=("generate",)) -> int:
        """Compile every (mode, bucket) executable up front.  Returns
        the number of compiles performed; after this, steady-state
        serving never compiles again (stats.compiles stays put)."""
        before = self.stats.compiles
        for mode in modes:
            if mode == "generate" and self.spec.cb_on:
                # cb replaces the generate buckets with exactly two
                # programs — prefill + decode step — whatever the
                # bucket list says; predict stays on buckets
                self._compile_cb("prefill")
                self._compile_cb("decode")
                continue
            for b, p in self.spec.buckets:
                self._compile(mode, b, p)
        for mode in modes:
            # from here on, a compile in this engine's scope for a
            # warmed mode family is a perf.recompile_anomaly
            perf.mark_warm(self._perf_scope, mode)
        return self.stats.compiles - before

    def harvest_costs(self) -> int:
        """CostWatch sweep: re-harvest `cost_analysis()` off every
        already-compiled executable.  Reads cached objects only —
        never lowers or compiles — so `stats.compiles` is unchanged
        (the --perf-smoke gate).  Returns programs harvested."""
        with self._compile_lock:
            items = list(self._compiled.items())
        for key, compiled in items:
            perf.harvest(key[0], compiled)
        return len(items)

    # -- execution ----------------------------------------------------------
    def set_stall(self, seconds: float) -> None:
        """Latch `seconds` of host-side sleep onto every compiled call
        (0 clears it).  Benches/tests use this for deterministic
        per-engine targeting; the `engine.stall` fault site latches
        `spec.stall_fault_s` on whichever engine's thread it fires in."""
        self.stall_s = max(float(seconds), 0.0)

    def _maybe_stall(self) -> None:
        kind = faults.maybe_fault("engine.stall")
        if kind == "stall":
            self.stall_s = max(self.stall_s,
                               float(self.spec.stall_fault_s))
        if self.stall_s > 0:
            time.sleep(self.stall_s)

    def _next_key(self) -> np.ndarray:
        # raw threefry key data, built host-side: no jax dispatch (and
        # no trace) on the per-batch path
        with self._key_lock:
            n = self.spec.seed * 1000003 + self._key_counter
            self._key_counter += 1
        return np.array([(n >> 32) & 0xFFFFFFFF, n & 0xFFFFFFFF],
                        np.uint32)

    def run_batch(self, mode: str, tokens: np.ndarray,
                  plens: np.ndarray, params=None) -> np.ndarray:
        """Run one padded micro-batch through the bucket's compiled
        executable.  `tokens` (B, P) int32 LEFT-padded with
        spec.pad_id, `plens` (B,) int32 real prompt lengths.  `params`
        is the tree the dispatcher captured from `self.params` (falls
        back to the live tree for direct callers).  Returns (B,
        max_new_tokens) int32 for generate, (B, V) float32 next-token
        log-probs for predict."""
        if params is None:
            params = self._params
        b, p = tokens.shape
        # on the dispatch thread this nests under batcher.dispatch and
        # inherits its batch-M correlation id
        with obs.span("engine.run_batch", mode=mode, batch=b, plen=p):
            self._maybe_stall()
            compiled = self._compile(mode, b, p)
            tokens = jnp.asarray(tokens, jnp.int32)
            plens = jnp.asarray(plens, jnp.int32)
            t0 = time.perf_counter()
            if mode == "generate":
                out = compiled(params, tokens, plens, self._next_key())
            else:
                out = compiled(params, tokens, plens)
            out = np.asarray(out)
            perf.observe_step(mode, time.perf_counter() - t0)
            if mode == "generate":
                perf.mark_serving_ready()   # first warm token (latch)
        return out
