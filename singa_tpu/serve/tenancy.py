"""Tenancy: the blast-radius boundary between workloads sharing one
fleet.

PRs 11-15 gave the serving tier fleet-global protections — RetryBudget,
brownout fractions, Retry-After streaks, shed accounting, autoscaler
signals — so the first flash crowd from one workload degraded
*everyone*: a single misbehaving client could drain the shared retry
budget and starve interactive traffic it never touched.  This module
makes the tenant the unit of isolation (the serving analog of the
multi-workload argument in "TensorFlow: A system for large-scale
machine learning", arxiv 1605.08695):

  `TenantSpec`      one tenant's QoS envelope: a guaranteed retry-
                    budget floor, queue/slot/KV-block quota fractions,
                    and optional brownout-fraction overrides.
  `TenantBudget`    a per-tenant child of the global `qos.RetryBudget`:
                    spends draw the tenant's private floor bucket
                    FIRST, then the shared bucket — so one tenant's
                    straggler storm can exhaust the shared tokens but
                    never another tenant's floor.  Earns refill the
                    private floor first; overflow earns into the
                    shared bucket, so the total-inflow arithmetic of
                    the global budget is preserved.
  `TenantRegistry`  the configured tenant set.  `default` is the
                    legacy tenant (no `X-Tenant` header) and always
                    exists; every UNCONFIGURED tenant id folds into
                    one shared `other` envelope — bounded memory,
                    bounded metric label cardinality (a tenant-id
                    fuzzer pays into `other`, it cannot blow up
                    `/metrics` or starve `default`), and an honest
                    rule: isolation is something you configure, not
                    something a header invents.

Spec grammar (`--tenant_spec`): tenants separated by `;`, fields by
`,`, the first field the tenant name, the rest `key=value` floats:

    "a,queue_frac=0.25,budget_floor=4;b,queue_frac=0.5"

`other` may be configured explicitly to clamp what unconfigured ids
collectively get.  Unknown keys and malformed entries raise (the CLI's
fail-fast contract); unknown tenant IDS at request time never do —
see `qos.check_tenant`.
"""

from __future__ import annotations

import dataclasses
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

from . import qos

#: the fold target for every unconfigured tenant id
TENANT_OTHER = "other"
#: the legacy tenant (requests without an X-Tenant header)
TENANT_DEFAULT = "default"


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's QoS envelope.  Fractions are shares of the
    enforcing component's capacity (queue depth, cb slots, KV pool
    blocks); 1.0 = no quota.  `budget_floor` is the guaranteed
    retry/hedge token floor (0 = no floor: pure shared-bucket
    behavior, what `default` gets unless configured).  Brownout
    overrides of 0.0 inherit the engine's fractions."""
    name: str
    budget_floor: float = 2.0
    queue_frac: float = 1.0
    slot_frac: float = 1.0
    kv_frac: float = 1.0
    brownout_be_frac: float = 0.0     # 0 = inherit ServeSpec
    brownout_batch_frac: float = 0.0  # 0 = inherit ServeSpec

    def __post_init__(self):
        name = str(self.name)
        if not name or name != qos.check_tenant(name):
            raise ValueError(
                f"bad tenant name {self.name!r}: want 1-64 chars of "
                f"[a-z0-9_-]")
        if float(self.budget_floor) < 0:
            raise ValueError(f"tenant {name}: budget_floor must be "
                             f">= 0, got {self.budget_floor}")
        for field in ("queue_frac", "slot_frac", "kv_frac"):
            v = float(getattr(self, field))
            if not 0 < v <= 1:
                raise ValueError(f"tenant {name}: {field} must be in "
                                 f"(0, 1], got {v}")
        for field in ("brownout_be_frac", "brownout_batch_frac"):
            v = float(getattr(self, field))
            if not 0 <= v <= 1:
                raise ValueError(f"tenant {name}: {field} must be in "
                                 f"[0, 1] (0 = inherit), got {v}")


class TenantBudget:
    """Per-tenant view of the global `qos.RetryBudget` with a
    guaranteed floor.  The private floor bucket starts full (mirroring
    RetryBudget's burst) and refills ONLY from this tenant's own
    earns, so another tenant's retry storm — which drains the shared
    bucket — leaves this tenant's floor tokens untouched.  A zero
    floor degenerates to the shared bucket exactly (the legacy
    single-tenant arithmetic)."""

    def __init__(self, shared: qos.RetryBudget, floor: float = 0.0):
        self.shared = shared
        self.floor = max(float(floor), 0.0)
        self._tokens = self.floor
        self._lock = threading.Lock()

    def earn(self, n: int = 1) -> None:
        """One primary dispatch: top up the private floor first;
        whatever does not fit earns into the shared bucket (same
        ratio), keeping total inflow identical to the pre-tenancy
        global bucket."""
        add = self.shared.ratio * n
        with self._lock:
            take = min(add, max(self.floor - self._tokens, 0.0))
            self._tokens += take
        rem = add - take
        if rem > 0 and self.shared.ratio > 0:
            self.shared.earn(rem / self.shared.ratio)

    def spend(self, n: float = 1.0) -> bool:
        """One retry/hedge/resume: the private floor pays first, then
        the shared bucket."""
        with self._lock:
            if self._tokens >= n:
                self._tokens -= n
                return True
        return self.shared.spend(n)

    def refund(self, n: float = 1.0) -> None:
        """Reverse of spend for a dispatch that never happened: refill
        the floor first, overflow back to the shared bucket."""
        with self._lock:
            take = min(n, max(self.floor - self._tokens, 0.0))
            self._tokens += take
        rem = n - take
        if rem > 0:
            self.shared.refund(rem)

    def tokens(self) -> float:
        """Floor tokens only (the shared bucket reports its own)."""
        with self._lock:
            return self._tokens


class TenantRegistry:
    """The configured tenant set and its per-tenant envelopes.  All
    lookups are by FOLDED label: a configured name (always including
    `default`) maps to itself, everything else to `other` — the one
    rule that bounds memory, metric cardinality, and blast radius at
    the same time."""

    def __init__(self, specs: Iterable[TenantSpec] = ()):
        self._specs: Dict[str, TenantSpec] = {}
        for spec in specs:
            if spec.name in self._specs:
                raise ValueError(f"duplicate tenant {spec.name!r}")
            self._specs[spec.name] = spec
        # default + other always exist; unconfigured = no floor, no
        # quota — exact legacy behavior for legacy clients
        for name in (TENANT_DEFAULT, TENANT_OTHER):
            self._specs.setdefault(
                name, TenantSpec(name=name, budget_floor=0.0))
        self._budgets: Dict[str, TenantBudget] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: Optional[str]) -> "TenantRegistry":
        """`"a,queue_frac=0.25,budget_floor=4;b,queue_frac=0.5"` —
        see the module docstring."""
        specs = []
        fields = {f.name for f in dataclasses.fields(TenantSpec)
                  if f.name != "name"}
        for entry in (spec or "").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = [p.strip() for p in entry.split(",") if p.strip()]
            name, kw = parts[0], {}
            for part in parts[1:]:
                key, sep, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or key not in fields:
                    raise ValueError(
                        f"bad tenant spec entry {part!r} for tenant "
                        f"{name!r} (want key=value with keys "
                        f"{sorted(fields)})")
                try:
                    kw[key] = float(val)
                except ValueError as e:
                    raise ValueError(
                        f"bad tenant spec value {part!r} for tenant "
                        f"{name!r}: {e}") from e
            specs.append(TenantSpec(name=name, **kw))
        return cls(specs)

    # -- lookups (all label-folded) -----------------------------------------
    def label(self, tenant: Optional[str]) -> str:
        """Fold a raw tenant id into its accounting/metrics label:
        configured names map to themselves, everything else to
        `other`."""
        t = qos.check_tenant(tenant)
        return t if t in self._specs else TENANT_OTHER

    def spec_for(self, tenant: Optional[str]) -> TenantSpec:
        return self._specs[self.label(tenant)]

    def labels(self) -> Tuple[str, ...]:
        """Every label that can appear on a `singa_tenant_*` series —
        the configured set; the bound the cardinality tests assert."""
        return tuple(sorted(self._specs))

    def names(self) -> Tuple[str, ...]:
        return self.labels()

    # -- budgets ------------------------------------------------------------
    def bind_budgets(self, shared: qos.RetryBudget) -> None:
        """Attach per-tenant child budgets to the shared bucket (the
        Router calls this once at construction)."""
        with self._lock:
            self._budgets = {
                name: TenantBudget(shared, spec.budget_floor)
                for name, spec in self._specs.items()}

    def budget(self, tenant: Optional[str]) -> TenantBudget:
        """The requesting tenant's budget view (label-folded).  Raises
        if `bind_budgets` was never called — budgets have no meaning
        without a shared bucket to draw from."""
        with self._lock:
            if not self._budgets:
                raise RuntimeError("TenantRegistry.bind_budgets() was "
                                   "never called")
            return self._budgets[self.label(tenant)]

    # -- quota arithmetic ---------------------------------------------------
    def queue_quota(self, tenant: Optional[str],
                    capacity: int) -> int:
        """Queued-request quota for one tenant against a queue of
        `capacity` (>= 1 so a quota can never starve a tenant of its
        last slot)."""
        frac = self.spec_for(tenant).queue_frac
        return max(int(frac * int(capacity)), 1)

    def slot_quota(self, tenant: Optional[str], slots: int) -> int:
        frac = self.spec_for(tenant).slot_frac
        return max(int(frac * int(slots)), 1)

    def kv_quota(self, tenant: Optional[str], blocks: int) -> int:
        frac = self.spec_for(tenant).kv_frac
        return max(int(frac * int(blocks)), 1)

    def brownout_fracs(self, tenant: Optional[str],
                       be_frac: float, batch_frac: float):
        """(be_frac, batch_frac) for one tenant: the tenant's
        overrides where configured (> 0), the engine's defaults
        otherwise."""
        spec = self.spec_for(tenant)
        be = spec.brownout_be_frac or float(be_frac)
        batch = spec.brownout_batch_frac or float(batch_frac)
        return be, batch

    def share(self, tenant: Optional[str]) -> float:
        """The tenant's quota share for capacity-signal weighting
        (autoscaler): a tenant limited to a fraction of the queue
        browning out its own overflow is the quota system working,
        not a reason to buy capacity — its sheds count at its
        share."""
        return float(self.spec_for(tenant).queue_frac)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        out = {}
        with self._lock:
            budgets = dict(self._budgets)
        for name, spec in sorted(self._specs.items()):
            row = {k: float(getattr(spec, k))
                   for k in ("budget_floor", "queue_frac", "slot_frac",
                             "kv_frac")}
            b = budgets.get(name)
            if b is not None:
                row["floor_tokens"] = round(b.tokens(), 3)
            out[name] = row
        return out


class TenantCounts:
    """Bounded per-(tenant, field) counters plus per-tenant latency
    reservoirs — the accounting both `RouterStats` and `ServeStats`
    export as labeled `singa_tenant_*` series.  Keys are folded labels
    (callers fold through a registry); a hard `max_tenants` cap folds
    anything beyond it into `other` anyway, so even an unfolded caller
    cannot grow this without bound.  The accounting identity the
    cardinality tests assert: for any field, the sum over tenant
    labels equals the number of `count` calls — nothing is dropped on
    fold, it lands in `other`."""

    def __init__(self, fields: Tuple[str, ...],
                 max_tenants: int = 64, window: int = 2048):
        self.fields = tuple(fields)
        self.max_tenants = int(max_tenants)
        self._lock = threading.Lock()
        self._counts: Dict[str, Dict[str, int]] = {}
        self._lat: Dict[str, list] = {}
        self._window = int(window)

    def _fold(self, tenant: str) -> str:
        if tenant in self._counts or tenant in self._lat:
            return tenant
        n = len(set(self._counts) | set(self._lat))
        # reserve one slot for the overflow bucket so the bound is
        # exact: at most `max_tenants` labels INCLUDING `other`
        if tenant != TENANT_OTHER and n >= self.max_tenants - 1:
            return TENANT_OTHER
        return tenant

    def count(self, field: str, tenant: str, n: int = 1) -> None:
        if field not in self.fields:
            raise ValueError(f"unknown tenant counter {field!r}")
        with self._lock:
            label = self._fold(tenant)
            row = self._counts.setdefault(label, {})
            row[field] = row.get(field, 0) + n

    def observe_latency(self, seconds: float, tenant: str) -> None:
        with self._lock:
            label = self._fold(tenant)
            lat = self._lat.setdefault(label, [])
            lat.append(float(seconds))
            if len(lat) > self._window:
                del lat[:len(lat) - self._window]

    def p95_ms(self, tenant: str) -> Optional[float]:
        with self._lock:
            lat = sorted(self._lat.get(tenant, ()))
        if not lat:
            return None
        idx = min(int(0.95 * len(lat)), len(lat) - 1)
        return round(lat[idx] * 1e3, 3)

    def tenants(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(set(self._counts) | set(self._lat)))

    def get(self, field: str, tenant: str) -> int:
        with self._lock:
            return self._counts.get(tenant, {}).get(field, 0)

    def totals(self) -> Dict[str, int]:
        """Per-field totals across every tenant label — the right side
        of the accounting identity."""
        out = {f: 0 for f in self.fields}
        with self._lock:
            for row in self._counts.values():
                for field, n in row.items():
                    out[field] += n
        return out

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            tenants = tuple(sorted(set(self._counts) | set(self._lat)))
        out = {}
        for t in tenants:
            with self._lock:
                row = dict(self._counts.get(t, {}))
            row["p95_ms"] = self.p95_ms(t)
            out[t] = row
        return out

    def register_into(self, registry,
                      prefix: str = "singa_tenant") -> None:
        """Labeled `singa_tenant_*` series: one sample per (field,
        tenant label) plus a per-tenant p95 gauge.  Cardinality is
        bounded by construction — `max_tenants` labels at most."""
        from ..obs.metrics import Sample

        def collect():
            out = []
            for t in self.tenants():
                labels = (("tenant", t),)
                with self._lock:
                    row = dict(self._counts.get(t, {}))
                for field in self.fields:
                    out.append(Sample(
                        f"{prefix}_{field}_total", "counter",
                        f"per-tenant counter {field!r}",
                        float(row.get(field, 0)), labels))
                p95 = self.p95_ms(t)
                if p95 is not None:
                    out.append(Sample(
                        f"{prefix}_p95_ms", "gauge",
                        "per-tenant p95 latency (ms)", p95, labels))
            return out

        registry.register_collector(collect)
