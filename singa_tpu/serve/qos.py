"""Request-lifecycle QoS primitives shared across the serving stack:
priority classes, end-to-end deadlines, the global retry budget, and
per-class shed backoffs.

Deadlines ("RPC Considered Harmful", arxiv 1805.08430): a client
timeout re-invented at every hop lets a request burn the full budget
per hop — four 5s hops serve a client who gave up 15s ago.  Here the
deadline is ONE absolute instant carried on the request: in-process as
a `time.monotonic()` value, across HTTP as the *remaining* budget in
milliseconds (`X-Deadline-Ms` — monotonic clocks are not comparable
across processes, so the receiver re-anchors remaining-ms onto its own
clock, the gRPC convention).  Every hop admits against what is LEFT;
an engine never prefills a request that is already dead on arrival
(counted `expired_on_arrival`), and a router retry can never outlive
the client's deadline.

Priority classes: `interactive` (a user is watching), `batch`
(pipelines; minutes of slack), `best_effort` (scavenger load).  Under
pressure admission sheds lowest class first — brownout — with an
honest per-class Retry-After: lower classes start (and cap) higher, so
the backoff hints themselves push background load out of the way of
interactive traffic.

Retry budget ("The Tail at Scale"): unbounded per-request retries turn
a brownout into a retry storm exactly when capacity is lowest.  The
`RetryBudget` token bucket earns a fraction of a token per PRIMARY
dispatch and spends one per retry or hedge, so fleet-wide retry
amplification is arithmetically capped at (1 + ratio) regardless of
failure pattern.  Exhaustion degrades to single-shot dispatch — the
request's first outcome stands; it is never shed *because* the budget
ran dry.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..utils import faults

PRIORITIES = ("interactive", "batch", "best_effort")

#: HTTP header carrying the remaining deadline budget in milliseconds
#: (re-anchored onto the receiver's monotonic clock)
DEADLINE_HEADER = "X-Deadline-Ms"
PRIORITY_HEADER = "X-Priority"

#: Tenant id header (serve/tenancy.py).  Degrade-never-reject: a
#: missing/blank/oversized/garbled value falls back to the `default`
#: tenant — tenancy is an isolation boundary, not an auth gate, and a
#: bad tenant header must never 400 a request
TENANT_HEADER = "X-Tenant"

#: W3C-traceparent-style trace context pair: the trace id is minted
#: once at the request's root span and carried VERBATIM on every hop
#: (frontend → router → worker, hedge legs, failover resumes,
#: /admin/reload); the parent span id lets the receiver anchor its
#: own spans under the caller's, so a merged trace reads as one tree
TRACE_HEADER = "X-Trace-Id"
PARENT_SPAN_HEADER = "X-Parent-Span"

#: Durable stream identity (serve/sessionlog.py): the sid is minted
#: at stream open, returned in the FIRST ndjson event (and this
#: response header), and presented back by a reconnecting client to
#: attach to the journaled continuation exactly-once after a router
#: crash or handoff
SESSION_HEADER = "X-Session-Id"

#: The serving router's fencing epoch, echoed on every response: a
#: client (or standby) seeing the epoch move knows a
#: restart/handoff happened even before any stream breaks
EPOCH_HEADER = "X-Router-Epoch"

#: Retry-After escalation factor per class: lower classes are told to
#: stay away longer, so honest hints do the brownout's first pass
_CLASS_FACTORS = (("interactive", 1.0), ("batch", 2.0),
                  ("best_effort", 4.0))


def check_priority(priority: Optional[str]) -> str:
    """Normalize and validate a priority class (None = interactive).
    Raises ValueError (the HTTP layer's 400) on an unknown class."""
    if priority is None:
        return "interactive"
    p = str(priority).strip().lower()
    if p not in PRIORITIES:
        raise ValueError(f"unknown priority {priority!r}; classes are "
                         f"{PRIORITIES}")
    return p


def check_tenant(tenant: Optional[str]) -> str:
    """Normalize a tenant id (None/blank = the `default` tenant).
    NEVER raises: an unparseable or hostile tenant id degrades to a
    sanitized string — quota lookup folds unknown ids into the shared
    `other` envelope, so garbage in the header costs the sender, not
    the request.  Ids are trimmed, lowercased, and truncated to 64
    chars; characters outside [a-z0-9_-] become `_`."""
    if tenant is None:
        return "default"
    t = str(tenant).strip().lower()[:64]
    if not t:
        return "default"
    return "".join(c if (c.isalnum() and c.isascii()) or c in "_-"
                   else "_" for c in t)


def resolve_deadline(timeout: Optional[float],
                     deadline: Optional[float],
                     default_timeout_s: float) -> Optional[float]:
    """The request's ONE absolute monotonic deadline: an explicit
    `deadline` wins; otherwise derived from `timeout` (default
    `default_timeout_s`; <= 0 = no deadline)."""
    if deadline is not None:
        return float(deadline)
    t = default_timeout_s if timeout is None else float(timeout)
    return (time.monotonic() + t) if t and t > 0 else None


def remaining_s(deadline: Optional[float]) -> Optional[float]:
    """Seconds of budget left (may be <= 0: dead on arrival)."""
    if deadline is None:
        return None
    return deadline - time.monotonic()


def transport_budget(deadline: Optional[float],
                     timeout: Optional[float],
                     default_s: float,
                     slack_s: float = 30.0) -> float:
    """Socket/wait budget for one transport hop: base time plus
    dispatch slack.  With an end-to-end deadline the slack is CLAMPED
    to the remaining budget (floor 0.1 s) — a flat `+ 30.0` would let
    a socket outlive a 2 s client deadline by 30 s, holding the
    connection (and the engine slot behind it) long after the client
    gave up.  Without a deadline the old generous slack stands: there
    is no client budget to leak past."""
    rem = remaining_s(deadline)
    if rem is not None:
        base = max(rem, 0.1)
        return base + min(float(slack_s), base)
    base = timeout if timeout and timeout > 0 else default_s
    return max(float(base), 0.1) + float(slack_s)


def deadline_to_header(deadline: Optional[float]) -> Optional[str]:
    """Remaining-budget milliseconds for `X-Deadline-Ms` (floored at 0
    so a dead request still propagates as dead, not as no-deadline)."""
    rem = remaining_s(deadline)
    if rem is None:
        return None
    return str(max(int(rem * 1000), 0))


def trace_to_headers(ctx) -> dict:
    """Serialize an `obs.trace_context()` tuple — `(trace_id,
    span_id)` — into the trace header pair ({} when there is no open
    span / no session: tracing off must add zero bytes to the wire)."""
    if not ctx:
        return {}
    trace_id, span_id = ctx
    out = {}
    if trace_id:
        out[TRACE_HEADER] = str(trace_id)
        if span_id:
            out[PARENT_SPAN_HEADER] = str(span_id)
    return out


def trace_from_headers(trace_id: Optional[str],
                       parent_span: Optional[str]):
    """Parse the receive side back into `(trace_id, parent_span_id)`,
    or None when no trace id was sent.  A malformed parent span id
    degrades to 0 (root of a remote track) — a trace header must
    never 400 a request that telemetry merely rides along on."""
    if trace_id is None or not str(trace_id).strip():
        return None
    try:
        psid = int(str(parent_span).strip()) if parent_span else 0
    except (TypeError, ValueError):
        psid = 0
    return (str(trace_id).strip(), psid)


def deadline_from_header(value: Optional[str]) -> Optional[float]:
    """Re-anchor a remaining-ms header onto THIS process's monotonic
    clock (monotonic instants are not comparable across processes)."""
    if value is None or str(value).strip() == "":
        return None
    return time.monotonic() + float(value) / 1000.0


# -- header <-> binary-frame mapping (serve/wire.py) -------------------------
# The binary transport carries the SAME QoS envelope as the HTTP
# headers, as flat struct fields instead of strings: remaining-ms
# deadline (i64, -1 = none, re-anchored by the receiver exactly like
# X-Deadline-Ms), a u8 priority code, and the tenant/trace/session ids
# as length-prefixed strings.  These helpers are the single source of
# truth for both directions so the two wire surfaces can never drift.

#: u8 priority code meaning "unspecified" (receiver defaults to
#: interactive, matching a missing X-Priority header)
PRIORITY_NONE_CODE = 255


def priority_to_code(priority: Optional[str]) -> int:
    """Priority class -> u8 frame code (index into PRIORITIES;
    PRIORITY_NONE_CODE for None).  Raises ValueError on an unknown
    class, same as check_priority."""
    if priority is None:
        return PRIORITY_NONE_CODE
    return PRIORITIES.index(check_priority(priority))


def priority_from_code(code: int) -> Optional[str]:
    """u8 frame code -> priority class (None for PRIORITY_NONE_CODE).
    An out-of-range code raises ValueError — unlike a garbled tenant,
    a bad priority code means the frame itself is skewed (the codec
    maps it to a malformed-frame close, the binary twin of the 400)."""
    c = int(code)
    if c == PRIORITY_NONE_CODE:
        return None
    if not 0 <= c < len(PRIORITIES):
        raise ValueError(f"unknown priority code {c}")
    return PRIORITIES[c]


def deadline_to_ms(deadline: Optional[float]) -> int:
    """Remaining-budget milliseconds for the frame header (-1 = no
    deadline; floored at 0 so a dead request propagates as dead —
    the flat-struct twin of deadline_to_header)."""
    rem = remaining_s(deadline)
    if rem is None:
        return -1
    return max(int(rem * 1000), 0)


def deadline_from_ms(ms: int) -> Optional[float]:
    """Re-anchor a remaining-ms frame field onto THIS process's
    monotonic clock (the frame twin of deadline_from_header)."""
    m = int(ms)
    if m < 0:
        return None
    return time.monotonic() + m / 1000.0


class RetryBudget:
    """Global token bucket bounding retries + hedges to a fraction of
    primary traffic.  `earn()` once per primary dispatch adds `ratio`
    tokens (capped at `burst`); `spend()` takes one whole token per
    retry/hedge or answers False.  With ratio r, total dispatches can
    never exceed (1 + r) x primaries + burst — a retry storm is
    arithmetically impossible, not merely discouraged."""

    def __init__(self, ratio: float = 0.1, burst: float = 16.0):
        self.ratio = max(float(ratio), 0.0)
        self.burst = max(float(burst), 0.0)
        self._tokens = self.burst
        self._lock = threading.Lock()

    def earn(self, n: int = 1) -> None:
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + self.ratio * n)

    def spend(self, n: float = 1.0) -> bool:
        with self._lock:
            if self._tokens < n:
                return False
            self._tokens -= n
            return True

    def refund(self, n: float = 1.0) -> None:
        """Return a token whose dispatch never happened (no sibling
        engine, hedge fault) — spend/refund stays conservative."""
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)

    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class ClassBackoffs:
    """Per-(tenant, priority-class) shed Retry-After: each stream
    escalates over ITS consecutive sheds and resets on ITS next
    successful admission, with lower classes starting (and capping)
    `_CLASS_FACTORS` higher.  Streaks are scoped per TENANT as well as
    per class: before tenancy, any successful dispatch reset the
    escalation streak for everyone, so a busy tenant's completions
    masked another tenant's congestion and its Retry-After never
    escalated.  The `default` tenant's interactive stream reproduces
    the single-class Backoff the admission paths used before
    priorities existed.

    Distinct tenant keys are bounded (`max_tenants`): callers normally
    pass registry-folded labels, but a raw-id caller cannot grow this
    dict without bound either — overflow tenants share the `other`
    stream."""

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 seed: int = 0, max_tenants: int = 64):
        self._lock = threading.Lock()
        self._base, self._cap, self._seed = base, cap, seed
        self.max_tenants = int(max_tenants)
        self._backoffs = {}
        self._streaks = {}
        self._tenants = set()
        for pri, _ in _CLASS_FACTORS:
            self._ensure("default", pri)

    def _factor(self, priority: str) -> float:
        for pri, factor in _CLASS_FACTORS:
            if pri == priority:
                return factor
        return 1.0

    def _key(self, tenant: str, priority: str):
        """Fold an unseen tenant into `other` once the bound is hit
        (lock held by caller)."""
        if tenant not in self._tenants:
            if len(self._tenants) >= self.max_tenants:
                tenant = "other"
            self._tenants.add(tenant)
        return (tenant, priority)

    def _ensure(self, tenant: str, priority: str):
        key = self._key(tenant, priority)
        if key not in self._backoffs:
            i = len(self._backoffs)
            f = self._factor(priority)
            self._backoffs[key] = faults.Backoff(
                base=self._base * f, cap=self._cap * f,
                seed=self._seed + i)
            self._streaks[key] = 0
        return key

    def shed_delay(self, priority: str,
                   tenant: str = "default") -> float:
        """Record one shed of (tenant, priority); the Retry-After to
        hint."""
        with self._lock:
            key = self._ensure(tenant, priority)
            self._streaks[key] += 1
            attempt = self._streaks[key]
            backoff = self._backoffs[key]
        return backoff.delay(attempt - 1)

    def reset(self, priority: str, tenant: str = "default") -> None:
        """A successful admission of (tenant, priority) ends its
        streak — and ONLY its streak: another tenant's congestion
        keeps escalating."""
        with self._lock:
            key = self._ensure(tenant, priority)
            self._streaks[key] = 0

    def streak(self, priority: str, tenant: str = "default") -> int:
        with self._lock:
            key = self._ensure(tenant, priority)
            return self._streaks[key]

    def export_streaks(self) -> dict:
        """Nonzero streaks as a JSON-safe dict (control-state
        snapshot): a tenant mid-escalation must NOT get a fresh
        Retry-After ladder just because the router restarted."""
        with self._lock:
            return {f"{t}\t{p}": s
                    for (t, p), s in self._streaks.items() if s}

    def restore_streaks(self, streaks: dict) -> None:
        with self._lock:
            for key, s in (streaks or {}).items():
                tenant, _, priority = str(key).partition("\t")
                try:
                    n = max(int(s), 0)
                except (TypeError, ValueError):
                    continue
                if not priority:
                    continue
                k = self._ensure(tenant, priority)
                self._streaks[k] = n
