"""Paged KV cache: a fixed pool of key/value blocks shared by every
serving slot, with host-side block tables and refcounts.

The static bucket path allocates each batch a contiguous
(B, Hkv, max_len, D) cache — O(max_len) per slot whether the sequence
uses it or not, and the whole allocation lives until the slowest
sequence in the batch finishes.  The paged layout cuts slot memory to
O(active tokens): every kAttention layer owns one
(num_blocks, Hkv, block_len, D) pool per side, a slot holds an ordered
list of block indices (its *block table* row), and retiring a slot
returns its blocks to the free list immediately — the memory shape
BASELINE.md's decode sweep says the tok/s ceiling lives in (the cache
read overtakes the weight read at batch 64; reads here stay at Hkv
width exactly like `_attn_cached`).

Split of responsibilities:

  * device side (jnp arrays in `pools`) — written/read only by the
    engine's two compiled cb programs (`models.generate.forward_paged`
    / `scatter_prefill`).  Block 0 is a reserved NULL block: inactive
    slots and table-tail entries point at it, so masked writes/reads
    land somewhere harmless and the compiled geometry never needs a
    "no block" special case.
  * host side (this class) — free list, per-block refcounts and the
    (num_slots, max_blocks_per_slot) int32 block table.  All
    bookkeeping is plain numpy under the scheduler's single thread; no
    jax dispatch happens here.

Blocks are reserved *conservatively at admission*: the scheduler asks
for ceil((plen + max_new) / block_len) blocks up front, so pool
exhaustion can only ever surface as an admission decision (queue, then
shed) — never as a mid-decode OOM or a deadlock between half-admitted
requests.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

Pools = Dict[str, Dict[str, jnp.ndarray]]   # layer -> {"k","v"} pools

NULL_BLOCK = 0


def init_pools(net, num_blocks: int, block_len: int,
               dtype=jnp.float32) -> Pools:
    """Zeroed (num_blocks, Hkv, block_len, D) k/v pools for every
    kAttention layer (the paged sibling of `generate.init_cache`)."""
    pools: Pools = {}
    for name in net.topo:
        layer = net.layers[name]
        if layer.cfg.type != "kAttention":
            continue
        shape = (num_blocks, layer.kv_heads, block_len, layer.head_dim)
        pools[name] = {"k": jnp.zeros(shape, dtype),
                       "v": jnp.zeros(shape, dtype)}
    return pools


def pool_bytes(net, num_blocks: int, block_len: int,
               dtype=jnp.float32) -> int:
    """Analytic byte count of the pools `init_pools` would allocate —
    k and v per kAttention layer, (num_blocks, Hkv, block_len, D)
    each.  MemoryWatch's HBM fallback on backends that expose no
    `memory_stats()` (the CPU test platform) uses this, so it must
    track `init_pools` shape-for-shape."""
    elems = 0
    for name in net.topo:
        layer = net.layers[name]
        if layer.cfg.type != "kAttention":
            continue
        elems += 2 * num_blocks * layer.kv_heads * block_len * layer.head_dim
    return elems * int(np.dtype(dtype).itemsize)


class PagedKVCache:
    """Block pool + slot tables for one serving engine.  Single-owner:
    the `ContinuousScheduler` thread is the only mutator, so the
    bookkeeping needs no lock; `snapshot()` reads are approximate from
    other threads (ints are swapped atomically in CPython)."""

    def __init__(self, net, num_slots: int, max_blocks_per_slot: int,
                 num_blocks: int, block_len: int, dtype=jnp.float32):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (block 0 is the "
                             f"reserved null block), got {num_blocks}")
        if block_len < 1 or num_slots < 1 or max_blocks_per_slot < 1:
            raise ValueError("num_slots, max_blocks_per_slot and "
                             "block_len must all be >= 1")
        self.num_slots = int(num_slots)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.num_blocks = int(num_blocks)
        self.block_len = int(block_len)
        self.pools: Pools = init_pools(net, self.num_blocks,
                                       self.block_len, dtype)
        # host bookkeeping: block 0 never enters the free list
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._refcounts = np.zeros((self.num_blocks,), np.int32)
        self.tables = np.full((self.num_slots, self.max_blocks_per_slot),
                              NULL_BLOCK, np.int32)
        self._slot_blocks: Dict[int, List[int]] = {}

    # -- capacity -----------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        """Pool capacity excluding the null block."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.usable_blocks - len(self._free)

    def blocks_for(self, total_tokens: int) -> int:
        """Blocks a sequence of `total_tokens` (prompt + generated)
        needs — the conservative admission reservation."""
        return -(-max(int(total_tokens), 1) // self.block_len)

    def can_admit(self, nblocks: int) -> bool:
        return nblocks <= len(self._free)

    # -- slot lifecycle -----------------------------------------------------
    def alloc(self, slot: int, nblocks: int) -> np.ndarray:
        """Reserve `nblocks` blocks for `slot` (refcount 1 each) and
        return the slot's full table row (real blocks first, null
        padding after).  Raises RuntimeError when the pool cannot
        cover the reservation — the scheduler checks `can_admit`
        first, so reaching the raise is a bug, not backpressure."""
        if slot in self._slot_blocks:
            raise RuntimeError(f"slot {slot} already holds blocks")
        if nblocks > self.max_blocks_per_slot:
            raise ValueError(
                f"request needs {nblocks} blocks but a slot holds at "
                f"most {self.max_blocks_per_slot}")
        if nblocks > len(self._free):
            raise RuntimeError(
                f"block pool exhausted: need {nblocks}, "
                f"{len(self._free)} free")
        blocks = [self._free.pop() for _ in range(nblocks)]
        self._refcounts[blocks] += 1
        self.tables[slot] = NULL_BLOCK
        self.tables[slot, :nblocks] = blocks
        self._slot_blocks[slot] = blocks
        return self.tables[slot].copy()

    def free(self, slot: int) -> None:
        """Retire `slot`: drop each block's refcount and return
        zero-refcount blocks to the free list immediately."""
        blocks = self._slot_blocks.pop(slot, None)
        if blocks is None:
            return
        for b in blocks:
            self._refcounts[b] -= 1
            if self._refcounts[b] == 0:
                self._free.append(b)
        self.tables[slot] = NULL_BLOCK

    def free_all(self) -> None:
        for slot in list(self._slot_blocks):
            self.free(slot)

    # -- reads --------------------------------------------------------------
    def table_array(self) -> np.ndarray:
        """Copy of the (num_slots, max_blocks_per_slot) int32 block
        table for upload to the compiled decode program."""
        return self.tables.copy()

    def utilization(self) -> float:
        return (self.blocks_in_use / self.usable_blocks
                if self.usable_blocks else 0.0)

    def snapshot(self) -> Dict[str, Any]:
        return {"num_blocks": self.num_blocks,
                "usable_blocks": self.usable_blocks,
                "free_blocks": self.free_blocks,
                "blocks_in_use": self.blocks_in_use,
                "block_len": self.block_len,
                "num_slots": self.num_slots,
                "max_blocks_per_slot": self.max_blocks_per_slot,
                "utilization": round(self.utilization(), 4)}
