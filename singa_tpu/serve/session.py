"""Router-side durable decode sessions: the journal that makes a
stream survive engine death (docs/SERVING.md, "Mid-stream failover").

A stream's transport must not be its unit of failure ("RPC Considered
Harmful", arxiv 1805.08430): once tokens have flowed, the old commit
point turned every engine crash, silent stall, or drain-timeout into a
mid-stream RuntimeError on exactly the long, expensive streams.  The
state worth keeping alive is tiny and lives HERE, one hop above the
engines: the prompt, every emitted token with an absolute sequence
number, the serving fingerprint (checkpoint step), and the QoS
envelope (deadline / priority / max_new).

That journal is sufficient to resume because greedy decode is
bit-deterministic given (fingerprint, prompt, tokens-so-far) — the
same property the paged==contiguous parity rig proved.  On failover
the router re-admits (prompt ‖ emitted-prefix) as a fresh prefill on a
*different* engine pinned to the same fingerprint with
`resume_from=n`; the new leg numbers its tokens from n, and the
consumer loop dedupes by sequence number so the client sees every
index exactly once — at-most-once delivery, bit-identical to the
uninterrupted stream.

Lifecycle (the JOURNALED → FAILED-OVER → SPLICED arc in SERVING.md):

    JOURNALED    every active stream; tokens recorded as they pass
    FAILED-OVER  a leg died (transport break, idle watchdog,
                 drain-timeout kick) and a resume leg was admitted
    SPLICED      the resumed leg finished; the terminal `done` event
                 carries the FULL token list and `spliced: true`
    DONE/FAILED  terminal either way; `failover_stale` is the honest
                 terminal finish when no same-fingerprint engine
                 remains to resume onto

Every leg writes into its session's ONE event queue tagged with its
leg identity; a stalled old leg that wakes up after failover can only
produce already-journaled indices (dropped by the dedupe, counted
`dup_tokens`) or stale control events (ignored: wrong leg tag) — a
zombie leg can never corrupt the client stream.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

STREAM_STATES = ("journaled", "failed_over", "spliced", "done",
                 "failed", "failover_stale")

#: terminal-session retention defaults (SessionManager.configure):
#: closed sessions are kept briefly so a reconnecting client can
#: replay the finished stream (exactly-once attach), then evicted
SESSION_TTL_S = 300.0
SESSION_CAP = 1024


class StreamStats:
    """Fleet-wide stream-session counters, exported as
    `singa_stream_*` (RouterStats mold, failover edition)."""

    FIELDS = ("opened", "done", "failed", "failovers", "resumed",
              "spliced", "dup_tokens", "gap_events", "idle_timeouts",
              "kicked", "resume_faults", "resume_denied",
              "failover_stale", "sessions_evicted", "attached")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)

    def count(self, fieldname: str, n: int = 1) -> None:
        with self._lock:
            # getattr validates the field exactly like ServeStats.gauge
            setattr(self, fieldname, getattr(self, fieldname) + n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {f: getattr(self, f) for f in self.FIELDS}

    def register_into(self, registry,
                      prefix: str = "singa_stream") -> None:
        from ..obs.metrics import Sample

        def collect():
            snap = self.snapshot()
            return [Sample(f"{prefix}_{k}_total", "counter",
                           f"stream session counter {k!r}",
                           float(snap[k])) for k in self.FIELDS]

        registry.register_collector(collect)


class StreamSession:
    """One stream's durable state: everything needed to re-derive the
    continuation on another engine, and the dedupe cursor that makes
    the splice at-most-once."""

    def __init__(self, sid: str, prompt: np.ndarray,
                 max_new: Optional[int], deadline: Optional[float],
                 priority: str, engine: str, step: int,
                 corr: Optional[str] = None, trace=None,
                 tenant: str = "default",
                 family: Optional[str] = None):
        self.sid = sid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = (int(max_new) if max_new is not None else None)
        self.deadline = deadline
        self.priority = priority
        self.engine = engine          # current leg's engine
        self.step = int(step)         # with `family`, the serving
        self.family = family          # fingerprint (family, step) —
                                      # a resume must match BOTH
        # the tenant that owns the stream: every failover resume
        # charges ITS retry budget, and the splice accounting lands
        # on its singa_tenant_* series
        self.tenant = tenant
        # trace context of the originating request — `(trace_id,
        # root_span_id)` — so a failover leg admitted seconds later on
        # a different thread still lands in the SAME trace, tagged
        # with the originating corr (the old legs minted fresh chains
        # and the splice was invisible in any trace)
        self.corr = corr
        self.trace = trace
        self.emitted: List[int] = []  # the journal: token i at [i]
        self.next_i = 0               # dedupe cursor: next index owed
        self.resumes = 0
        self.state = "journaled"
        self.t0 = time.monotonic()
        # ONE queue for the session's whole life; every leg pumps into
        # it tagged with its leg object, kicks are tagged None — see
        # module docstring for why a zombie leg is harmless
        self.q: "queue.Queue" = queue.Queue()
        # replay buffer (crash recovery): a WAL-recovered stream has
        # no connected client, so the recovery driver parks its
        # spliced events here and a reconnecting client (attach by
        # X-Session-Id) drains them exactly-once from `resume_from`
        self.attachable = False
        self.replay: List[Dict[str, Any]] = []
        self.replay_done = False
        self.replay_cond = threading.Condition()

    def replay_append(self, ev: Dict[str, Any]) -> None:
        with self.replay_cond:
            self.replay.append(ev)
            self.replay_cond.notify_all()

    def replay_finish(self) -> None:
        with self.replay_cond:
            self.replay_done = True
            self.replay_cond.notify_all()

    def attach(self, resume_from: int = 0
               ) -> Iterator[Dict[str, Any]]:
        """Drain the replay buffer from token index `resume_from` —
        the reconnect path.  Token events below `resume_from` are
        skipped (the client already has them: exactly-once across
        the reconnect); control/terminal events always pass."""
        if not self.attachable:
            raise ValueError(f"session {self.sid!r} not attachable")
        pos = 0
        while True:
            with self.replay_cond:
                while (pos >= len(self.replay)
                       and not self.replay_done):
                    self.replay_cond.wait(0.25)
                evs = self.replay[pos:]
                done = self.replay_done
            pos += len(evs)
            for ev in evs:
                if ("token" in ev
                        and int(ev.get("i", 0)) < int(resume_from)):
                    continue
                yield ev
            if done:
                return

    def record(self, token: int) -> None:
        """Journal token `next_i` (caller already deduped by index)."""
        self.emitted.append(int(token))
        self.next_i += 1

    def resume_tokens(self) -> np.ndarray:
        """The re-admission prompt: original prompt ‖ emitted prefix —
        with the fingerprint, the complete decode state."""
        if not self.emitted:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.emitted, np.int32)])

    def kick(self, why: str) -> None:
        """Ask the consumer loop to fail over NOW (drain-timeout
        during scale-down): delivered through the session queue so it
        interrupts even a consumer parked waiting for the next
        token."""
        self.q.put((None, "kick", why))

    def snapshot(self) -> Dict[str, Any]:
        return {"sid": self.sid, "engine": self.engine,
                "step": self.step, "family": self.family,
                "tenant": self.tenant, "state": self.state,
                "emitted": len(self.emitted),
                "resumes": self.resumes,
                "age_s": round(time.monotonic() - self.t0, 3)}


class SessionManager:
    """The router's registry of live stream sessions: opens/closes
    them, owns the `singa_stream_*` stats, and fans a drain-timeout
    kick out to every session still on the doomed engine."""

    def __init__(self):
        self.stats = StreamStats()
        self._lock = threading.Lock()
        self._sessions: Dict[str, StreamSession] = {}
        # terminal sessions retained (bounded) for reconnect replay:
        # insertion order == close order, so TTL/cap eviction pops
        # from the front — `kick_engine` never scans these, keeping
        # it O(live) not O(ever-opened)
        self._terminal: "OrderedDict[str, Tuple[StreamSession, float]]" \
            = OrderedDict()
        self._ids = itertools.count(1)
        self.wal = None               # SessionWal when durability is on
        self.epoch = 0                # router epoch (0 = no WAL)
        self.ttl_s = SESSION_TTL_S
        self.cap = SESSION_CAP

    def configure(self, wal=None, epoch: int = 0,
                  ttl_s: Optional[float] = None,
                  cap: Optional[int] = None) -> None:
        """Attach the durability plumbing (fleet wires this before
        traffic): the WAL every open/token/close journals into, the
        router epoch that namespaces fresh sids (a restarted router
        must never mint a sid colliding with a journaled one), and
        the terminal-retention bounds."""
        self.wal = wal
        self.epoch = int(epoch)
        if ttl_s is not None:
            self.ttl_s = max(float(ttl_s), 0.0)
        if cap is not None:
            self.cap = max(int(cap), 0)

    def open(self, prompt, max_new: Optional[int],
             deadline: Optional[float], priority: str,
             engine: str, step: int, corr: Optional[str] = None,
             trace=None, tenant: str = "default",
             family: Optional[str] = None, sid: Optional[str] = None,
             emitted: Optional[List[int]] = None) -> StreamSession:
        if sid is None:
            n = next(self._ids)
            sid = (f"s{self.epoch}-{n}" if self.epoch
                   else f"stream-{n}")
        s = StreamSession(sid, prompt, max_new, deadline, priority,
                          engine, step, corr=corr, trace=trace,
                          tenant=tenant, family=family)
        # a recovered session re-enters with its journaled prefix
        for t in (emitted or []):
            s.record(int(t))
        with self._lock:
            self._sessions[sid] = s
            self._terminal.pop(sid, None)
        self.stats.count("opened")
        if self.wal is not None:
            rem = (max(deadline - time.monotonic(), 0.0)
                   if deadline is not None else None)
            # write-ahead of the first token: the open record is what
            # lets a post-crash replay re-derive the decode.  A
            # recovered open re-journals prefix and all into the NEW
            # epoch's WAL, so each journal is self-contained.
            self.wal.append_open(sid, s.prompt.tolist(), s.max_new,
                                 priority, tenant, family, step, rem)
            for i, t in enumerate(s.emitted):
                self.wal.append_tok(sid, i, t)
        self._evict()
        return s

    def close(self, session: StreamSession, state: str) -> None:
        session.state = state
        with self._lock:
            self._sessions.pop(session.sid, None)
            self._terminal[session.sid] = (
                session, time.monotonic() + self.ttl_s)
            self._terminal.move_to_end(session.sid)
        if self.wal is not None:
            self.wal.append_close(session.sid, state)
        if state in ("done", "spliced"):
            self.stats.count("done")
        elif state == "failover_stale":
            self.stats.count("failover_stale")
        else:
            self.stats.count("failed")
        self._evict()

    def get(self, sid: str) -> Optional[StreamSession]:
        """Live session, or a retained terminal one (reconnect)."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None:
                return s
            ent = self._terminal.get(sid)
            return ent[0] if ent is not None else None

    def register_terminal(self, rec: Dict[str, Any]
                          ) -> StreamSession:
        """Re-register a stream the WAL shows finished BEFORE the
        crash: replaying it is a pure journal read — a reconnecting
        client gets the journaled tokens + terminal event, and no
        engine ever re-decodes a finished stream."""
        s = StreamSession(
            rec["sid"], np.asarray(rec.get("prompt") or [], np.int32),
            rec.get("max_new"), None,
            rec.get("priority") or "interactive",
            rec.get("engine") or "", int(rec.get("step", -1)),
            tenant=rec.get("tenant") or "default",
            family=rec.get("family"))
        for t in rec.get("emitted") or []:
            s.record(int(t))
        s.state = rec.get("terminal") or "done"
        s.attachable = True
        for i, t in enumerate(s.emitted):
            s.replay.append({"token": int(t), "i": i})
        finish = ("length" if s.state in ("done", "spliced")
                  else s.state)
        s.replay.append({"done": True, "finish": finish,
                         "n": len(s.emitted),
                         "tokens": list(s.emitted),
                         "sid": s.sid, "step": s.step,
                         "replayed": True})
        s.replay_done = True
        with self._lock:
            self._terminal[s.sid] = (
                s, time.monotonic() + self.ttl_s)
        return s

    def _evict(self) -> None:
        """Lazy TTL/cap eviction of retained terminal sessions —
        the bound that keeps the manager O(live) forever."""
        now = time.monotonic()
        evicted = 0
        with self._lock:
            while self._terminal:
                _, (_, expiry) = next(iter(self._terminal.items()))
                if expiry <= now or len(self._terminal) > self.cap:
                    self._terminal.popitem(last=False)
                    evicted += 1
                else:
                    break
        if evicted:
            self.stats.count("sessions_evicted", evicted)

    def kick_engine(self, engine: str, why: str) -> int:
        """Fail every live session on `engine` over to a sibling
        (scale-down drain timed out: the engine is leaving whether its
        streams finished or not).  Returns how many were kicked."""
        with self._lock:
            doomed = [s for s in self._sessions.values()
                      if s.engine == engine]
        for s in doomed:
            s.kick(why)
        if doomed:
            self.stats.count("kicked", len(doomed))
        return len(doomed)

    def active(self) -> int:
        with self._lock:
            return len(self._sessions)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            sessions = [s.snapshot() for s in self._sessions.values()]
            retained = len(self._terminal)
        out: Dict[str, Any] = dict(self.stats.snapshot())
        out["active"] = len(sessions)
        out["terminal_retained"] = retained
        out["sessions"] = sessions
        return out
