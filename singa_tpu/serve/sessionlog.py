"""Durable session WAL + control-state snapshots: the router's crash
safety (docs/SERVING.md, "Control-plane durability").

PR 13 made streams survive *engine* death by journaling every token
one hop above the engines — but that journal lived in the router's
memory, so the router itself was still the fleet's single point of
loss: a crash, OOM-kill, or rolling upgrade destroyed every live
stream plus all quarantine/rollout/tenant state.  This module puts
the journal on disk with the same discipline `CheckpointManager`
uses for params:

  * **Write-ahead, group-committed.**  `SessionWal.append_*` is the
    streaming hot path: it coalesces records into an in-memory
    pending list under a lock (microseconds) and a flusher thread
    writes + fsyncs every `group_tokens` records / `group_ms`
    milliseconds — the disk is never on a token's critical path.  A
    failed write degrades to COUNTED lost durability (`wal_lost`,
    fault site `router.wal`); it never blocks or kills a stream.
  * **Torn-tail-tolerant replay.**  Every record is one ndjson line
    carrying a CRC32 of its body.  A SIGKILL mid-write leaves at most
    one torn final line; replay stops at the first unparsable or
    CRC-failing line (counted `torn_tails`) — a torn tail truncates,
    it never poisons the records before it.
  * **Epoch fencing.**  Each router instance claims a monotonically
    increasing epoch (`<dir>/EPOCH`, atomic write) and journals to
    `wal-<epoch>.ndjson` whose header record carries the epoch.  A
    fenced WAL (explicit `fence()` on handoff, or a newer epoch
    observed in the EPOCH file at flush time) refuses all writes
    (`fenced_writes`) so a replaced primary can never corrupt the
    successor's recovery source.

Record kinds (all idempotent under replay):

    header  {"k":"header","epoch":E,"ver":1,"wall":t}
    open    {"k":"open","sid":...,"prompt":[...],"max_new":n,
             "priority":p,"tenant":t,"family":f,"step":s,
             "deadline_rem_s":r}
    tok     {"k":"tok","sid":...,"i":i0,"t":[tokens...]}  (batched;
            duplicates after a crash-between-fsync-and-ack are folded
            by absolute index at replay)
    resume  {"k":"resume","sid":...,"engine":e,"at":n}
    close   {"k":"close","sid":...,"state":st}

`ControlStateStore` snapshots the slow-moving control state
(quarantine benches, rollout phase, tenant Retry-After streaks,
autoscaler cooldowns) to `<dir>/state.json` with the tmp + fsync +
rename discipline; a torn or missing snapshot degrades to empty
state, never a failed start.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..utils import faults

WAL_VERSION = 1
EPOCH_FILE = "EPOCH"
STATE_FILE = "state.json"


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + write + flush + fsync + rename — the CheckpointManager
    discipline: a reader sees the old file or the new file, never a
    torn one."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _crc(body: Dict[str, Any]) -> int:
    return zlib.crc32(
        json.dumps(body, sort_keys=True,
                   separators=(",", ":")).encode()) & 0xFFFFFFFF


def _encode(body: Dict[str, Any]) -> bytes:
    return json.dumps({"c": _crc(body), "r": body},
                      separators=(",", ":")).encode() + b"\n"


def wal_path(dir_: str, epoch: int) -> str:
    return os.path.join(dir_, f"wal-{int(epoch):08d}.ndjson")


def read_epoch(dir_: str) -> int:
    """The highest epoch ever claimed under `dir_` (0 = none)."""
    try:
        with open(os.path.join(dir_, EPOCH_FILE)) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def claim_epoch(dir_: str) -> int:
    """Claim the next epoch (atomic write).  Every router restart or
    standby promotion claims a FRESH epoch — the EPOCH file is the
    fencing token a stale primary's flusher checks itself against."""
    os.makedirs(dir_, exist_ok=True)
    epoch = read_epoch(dir_) + 1
    _atomic_write(os.path.join(dir_, EPOCH_FILE),
                  f"{epoch}\n".encode())
    return epoch


def latest_wal_before(dir_: str, epoch: int) -> Optional[str]:
    """The predecessor's journal: the highest-epoch WAL file strictly
    below `epoch` (the one a restarted/promoted router replays)."""
    best, best_e = None, -1
    try:
        names = os.listdir(dir_)
    except OSError:
        return None
    for n in names:
        if not (n.startswith("wal-") and n.endswith(".ndjson")):
            continue
        try:
            e = int(n[4:-7])
        except ValueError:
            continue
        if best_e < e < int(epoch):
            best, best_e = os.path.join(dir_, n), e
    return best


class WalStats:
    """WAL + recovery counters, exported as `singa_router_*_total`
    (the StreamStats mold)."""

    FIELDS = ("wal_appends", "wal_bytes", "wal_flushes", "wal_lost",
              "fenced_writes", "replayed_sessions",
              "recovered_streams", "torn_tails", "state_snapshots",
              "state_snapshot_failures")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)

    def count(self, fieldname: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, fieldname, getattr(self, fieldname) + n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {f: getattr(self, f) for f in self.FIELDS}

    def register_into(self, registry,
                      prefix: str = "singa_router") -> None:
        from ..obs.metrics import Sample

        def collect():
            snap = self.snapshot()
            return [Sample(f"{prefix}_{k}_total", "counter",
                           f"router WAL counter {k!r}",
                           float(snap[k])) for k in self.FIELDS]

        registry.register_collector(collect)


class SessionWal:
    """Append-only per-router session journal; see module docstring.
    Thread-safe: any number of appenders, one flusher."""

    def __init__(self, dir_: str, epoch: int,
                 group_tokens: int = 64, group_ms: float = 25.0,
                 stats: Optional[WalStats] = None, log_fn=print):
        os.makedirs(dir_, exist_ok=True)
        self.dir = dir_
        self.epoch = int(epoch)
        self.path = wal_path(dir_, epoch)
        self.group_tokens = max(int(group_tokens), 1)
        self.group_ms = max(float(group_ms), 0.0)
        self.stats = stats or WalStats()
        self.log = log_fn
        self._lock = threading.Lock()
        self._pending: List[Dict[str, Any]] = []
        self._pending_n = 0
        self._fenced = False
        self._closed = False
        self._file = open(self.path, "ab")
        self._wake = threading.Event()
        self._stop = threading.Event()
        # header first, synchronously: replay identifies the epoch
        # from the first record even if nothing else ever lands
        self._pending.append({"k": "header", "epoch": self.epoch,
                              "ver": WAL_VERSION,
                              "wall": round(time.time(), 6)})
        self.flush()
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"wal-{epoch}", daemon=True)
        self._flusher.start()

    @property
    def fenced(self) -> bool:
        return self._fenced

    # -- hot path -----------------------------------------------------------
    def _append(self, body: Dict[str, Any]) -> bool:
        with self._lock:
            if self._fenced or self._closed:
                self.stats.count("fenced_writes")
                return False
            self._pending.append(body)
            self._pending_n += 1
            n = self._pending_n
        self.stats.count("wal_appends")
        if n >= self.group_tokens:
            self._wake.set()
        return True

    def append_open(self, sid: str, prompt, max_new, priority: str,
                    tenant: str, family: Optional[str], step: int,
                    deadline_rem_s: Optional[float]) -> bool:
        return self._append({
            "k": "open", "sid": sid,
            "prompt": [int(t) for t in prompt],
            "max_new": max_new, "priority": priority,
            "tenant": tenant, "family": family, "step": int(step),
            "deadline_rem_s": deadline_rem_s})

    def append_tok(self, sid: str, i: int, token: int) -> bool:
        """One token by absolute index.  Coalesced in the pending
        buffer: consecutive tokens of one sid become ONE `tok`
        record, so the group-committed write is compact."""
        with self._lock:
            if self._fenced or self._closed:
                self.stats.count("fenced_writes")
                return False
            if self._pending:
                last = self._pending[-1]
                if (last.get("k") == "tok" and last["sid"] == sid
                        and last["i"] + len(last["t"]) == int(i)):
                    last["t"].append(int(token))
                    self._pending_n += 1
                    n = self._pending_n
                    self.stats.count("wal_appends")
                    if n >= self.group_tokens:
                        self._wake.set()
                    return True
            self._pending.append({"k": "tok", "sid": sid,
                                  "i": int(i), "t": [int(token)]})
            self._pending_n += 1
            n = self._pending_n
        self.stats.count("wal_appends")
        if n >= self.group_tokens:
            self._wake.set()
        return True

    def append_resume(self, sid: str, engine: str, at: int) -> bool:
        return self._append({"k": "resume", "sid": sid,
                             "engine": engine, "at": int(at)})

    def append_close(self, sid: str, state: str) -> bool:
        return self._append({"k": "close", "sid": sid,
                             "state": state})

    # -- group commit -------------------------------------------------------
    def _flush_loop(self) -> None:
        period = max(self.group_ms / 1e3, 0.001)
        while not self._stop.is_set():
            self._wake.wait(period)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        """Write + fsync everything pending (one group commit).  A
        write failure — injected `router.wal` fault or a real disk
        error — drops the batch as COUNTED lost durability and the
        stream keeps serving; durability degrades, tokens never
        block.  Also the fencing checkpoint: a newer epoch in the
        EPOCH file means a successor claimed over us — self-fence."""
        with self._lock:
            batch = self._pending
            n = self._pending_n
            self._pending = []
            self._pending_n = 0
        if not batch:
            return
        if read_epoch(self.dir) > self.epoch:
            with self._lock:
                if not self._fenced:
                    self._fenced = True
                    self.log(f"wal: epoch {self.epoch} fenced (a "
                             f"newer router claimed the journal)")
            self.stats.count("fenced_writes", max(n, 1))
            return
        try:
            faults.maybe_fault("router.wal")
            data = b"".join(_encode(b) for b in batch)
            self._file.write(data)
            self._file.flush()
            os.fsync(self._file.fileno())
            self.stats.count("wal_flushes")
            self.stats.count("wal_bytes", len(data))
        except Exception as e:  # noqa: BLE001 — degrade, never block
            self.stats.count("wal_lost", max(n, 1))
            self.log(f"warning: wal group commit dropped {n} "
                     f"record(s) ({type(e).__name__}: {e}); "
                     f"durability degraded, stream unaffected")

    def fence(self) -> None:
        """Refuse all future writes (handoff: the successor owns the
        journal from here).  Pending records are flushed FIRST so the
        successor's recovery source is complete up to the fence."""
        self.flush()
        with self._lock:
            self._fenced = True

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self.flush()
        with self._lock:
            self._closed = True
        try:
            self._file.close()
        except OSError:
            pass


# -- replay -----------------------------------------------------------------

def replay_wal(path: str) -> Tuple[Optional[Dict[str, Any]],
                                   List[Dict[str, Any]], bool]:
    """Read a WAL tolerating a torn tail: returns (header record or
    None, body records, torn?).  The first unparsable or CRC-failing
    line truncates the replay — everything before it is trusted,
    nothing after it is read (a torn record never poisons replay)."""
    header: Optional[Dict[str, Any]] = None
    records: List[Dict[str, Any]] = []
    torn = False
    try:
        f = open(path, "rb")
    except OSError:
        return None, [], False
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                body = rec["r"]
                if int(rec["c"]) != _crc(body):
                    raise ValueError("crc mismatch")
            except Exception:  # noqa: BLE001 — torn/corrupt line
                torn = True
                break
            if body.get("k") == "header" and header is None:
                header = body
            else:
                records.append(body)
    return header, records, torn


def reduce_sessions(records: List[Dict[str, Any]]
                    ) -> Dict[str, Dict[str, Any]]:
    """Fold a replayed record stream into per-session state.  Token
    records are applied idempotently by ABSOLUTE index, so a
    duplicate append after a crash-between-fsync-and-ack folds to a
    no-op; `terminal` is the journaled close state (None = the
    session was still live at the crash)."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        k, sid = rec.get("k"), rec.get("sid")
        if not sid:
            continue
        if k == "open":
            out[sid] = {
                "sid": sid, "prompt": list(rec.get("prompt") or []),
                "max_new": rec.get("max_new"),
                "priority": rec.get("priority") or "interactive",
                "tenant": rec.get("tenant") or "default",
                "family": rec.get("family"),
                "step": int(rec.get("step", -1)),
                "deadline_rem_s": rec.get("deadline_rem_s"),
                "engine": "", "emitted": [], "resumes": 0,
                "terminal": None}
            continue
        s = out.get(sid)
        if s is None:
            continue              # tok/close for an unjournaled open
        if k == "tok":
            i0, toks = int(rec.get("i", 0)), rec.get("t") or []
            for j, t in enumerate(toks):
                pos = i0 + j
                if pos < len(s["emitted"]):
                    continue      # duplicate append: idempotent fold
                if pos > len(s["emitted"]):
                    break         # gap: keep the contiguous prefix
                s["emitted"].append(int(t))
        elif k == "resume":
            s["resumes"] += 1
            s["engine"] = rec.get("engine") or s["engine"]
        elif k == "close":
            s["terminal"] = rec.get("state") or "done"
    return out


def walcheck(path: str) -> Dict[str, Any]:
    """Offline WAL validation/dump (tools/walcheck.py): replay the
    file and summarize what a recovery would see."""
    header, records, torn = replay_wal(path)
    sessions = reduce_sessions(records)
    live = {sid: s for sid, s in sessions.items()
            if s["terminal"] is None}
    kinds: Dict[str, int] = {}
    for r in records:
        kinds[r.get("k", "?")] = kinds.get(r.get("k", "?"), 0) + 1
    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    return {
        "path": path,
        "epoch": (header or {}).get("epoch"),
        "version": (header or {}).get("ver"),
        "bytes": size,
        "records": len(records),
        "by_kind": kinds,
        "torn_tail": torn,
        "sessions": len(sessions),
        "live_sessions": len(live),
        "closed_sessions": len(sessions) - len(live),
        "journaled_tokens": sum(len(s["emitted"])
                                for s in sessions.values()),
        "live": [{"sid": sid, "tokens": len(s["emitted"]),
                  "resumes": s["resumes"], "step": s["step"],
                  "family": s["family"], "tenant": s["tenant"]}
                 for sid, s in sorted(live.items())],
    }


# -- control-state snapshots ------------------------------------------------

class ControlStateStore:
    """Periodic atomic snapshots of the router's slow-moving control
    state (`<dir>/state.json`): quarantine strikes/benches, rollout
    phase + rejected fingerprints, tenant Retry-After streaks,
    autoscaler cooldowns.  `load()` is torn/missing-tolerant — a
    router with no snapshot starts from clean state, never refuses
    to start."""

    def __init__(self, dir_: str, stats: Optional[WalStats] = None):
        os.makedirs(dir_, exist_ok=True)
        self.path = os.path.join(dir_, STATE_FILE)
        self.stats = stats or WalStats()

    def save(self, state: Dict[str, Any]) -> bool:
        try:
            _atomic_write(self.path,
                          json.dumps(state, default=str).encode())
            self.stats.count("state_snapshots")
            return True
        except Exception:  # noqa: BLE001 — snapshot is best-effort
            self.stats.count("state_snapshot_failures")
            return False

    def load(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path) as f:
                out = json.load(f)
            return out if isinstance(out, dict) else None
        except (OSError, ValueError):
            return None
