"""TPU-native inference serving tier (docs/SERVING.md).

The first subsystem on the inference half of the north star: admit ->
micro-batch -> compiled bucket program -> respond, following the
trainer's checkpoints via atomic hot-reload.  Layers:

    engine.py   ServeSpec + InferenceEngine: AOT-compiled per-bucket
                generate/predict programs, healthy-checkpoint load,
                degrade-not-crash hot reload
    batcher.py  MicroBatcher: bounded-queue admission with Backoff
                shedding, deadline expiry, smallest-admissible-bucket
                coalescing with left-pad masking
    server.py   InferenceServer: stdlib-HTTP + in-process frontends,
                reload poll thread
    stats.py    ServeStats: QPS, p50/p95 latency, occupancy, queue
                depth, reload/shed counters (PipelineStats mold)

Fault sites `serve.admit` / `serve.batch` / `serve.reload`
(utils.faults) make every degradation path deterministic on CPU.
"""

from .batcher import DeadlineExpired, MicroBatcher, Overloaded, Ticket
from .engine import InferenceEngine, ServeSpec
from .server import InferenceServer
from .stats import ServeStats

__all__ = ["DeadlineExpired", "InferenceEngine", "InferenceServer",
           "MicroBatcher", "Overloaded", "ServeSpec", "ServeStats",
           "Ticket"]
