"""TPU-native inference serving tier (docs/SERVING.md).

The first subsystem on the inference half of the north star: admit ->
micro-batch -> compiled bucket program -> respond, following the
trainer's checkpoints via atomic hot-reload.  Layers:

    engine.py   ServeSpec + InferenceEngine: AOT-compiled per-bucket
                generate/predict programs, healthy-checkpoint load,
                degrade-not-crash hot reload, pinned-fingerprint fleet
                mode + explicit reload_to, honest health() verdicts
    batcher.py  MicroBatcher: bounded-queue admission with Backoff
                shedding, deadline expiry, smallest-admissible-bucket
                coalescing with left-pad masking
    server.py   InferenceServer: stdlib-HTTP + in-process frontends,
                reload poll thread, /admin/reload command channel
    stats.py    ServeStats: QPS, p50/p95 latency, occupancy, queue
                depth, reload/shed counters (PipelineStats mold)
    router.py   Router + engine handles: least-loaded healthy
                dispatch, retry-on-other-engine, Backoff quarantine /
                readmission, router-level shedding
    fleet.py    EngineFleet + RolloutController + FleetServer:
                N workers behind one router, canary rollout with
                auto-rollback (OBSERVE -> CANARY -> PROMOTE/ROLLBACK)

Fault sites `serve.admit` / `serve.batch` / `serve.reload` /
`fleet.dispatch` / `fleet.rollout` (utils.faults) make every
degradation path deterministic on CPU.
"""

from .batcher import DeadlineExpired, MicroBatcher, Overloaded, Ticket
from .engine import InferenceEngine, ServeSpec
from .fleet import (EngineFleet, FleetServer, RolloutController,
                    RolloutSpec)
from .router import (EngineUnavailable, HttpEngineHandle,
                     LocalEngineHandle, Router, RouterSpec,
                     RouterStats)
from .server import InferenceServer
from .stats import ServeStats

__all__ = ["DeadlineExpired", "EngineFleet", "EngineUnavailable",
           "FleetServer", "HttpEngineHandle", "InferenceEngine",
           "InferenceServer", "LocalEngineHandle", "MicroBatcher",
           "Overloaded", "RolloutController", "RolloutSpec", "Router",
           "RouterSpec", "RouterStats", "ServeSpec", "ServeStats",
           "Ticket"]
