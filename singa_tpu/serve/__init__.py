"""TPU-native inference serving tier (docs/SERVING.md).

The first subsystem on the inference half of the north star: admit ->
batch -> compiled program -> respond, following the trainer's
checkpoints via atomic hot-reload.  Layers:

    engine.py    ServeSpec + InferenceEngine: AOT-compiled per-bucket
                 generate/predict programs, healthy-checkpoint load,
                 degrade-not-crash hot reload, pinned-fingerprint fleet
                 mode + explicit reload_to, honest health() verdicts;
                 cb=on adds the two continuous-batching programs
                 (paged prefill + fixed-slot decode step)
    batcher.py   MicroBatcher: bounded-queue admission with Backoff
                 shedding, deadline expiry, smallest-admissible-bucket
                 coalescing with left-pad masking (the static path;
                 predict always rides here)
    kvcache.py   PagedKVCache: fixed pool of (block, Hkv, block_len,
                 D) KV blocks, per-slot block tables, refcounts, null
                 block 0 — slot memory O(active tokens)
    scheduler.py ContinuousScheduler + StreamTicket: admit a request
                 into a free slot at any decode step, retire on
                 EOS/max-new/deadline, free blocks immediately, ONE
                 compiled decode program per step
    server.py    InferenceServer: stdlib-HTTP + in-process frontends,
                 reload poll thread, /admin/reload command channel,
                 chunked-transfer streaming POST /generate under cb
    stats.py     ServeStats: QPS, p50/p95 latency + queue-wait/
                 service split, tok/s, occupancy (bucket and slot),
                 reload/shed counters (PipelineStats mold)
    router.py    Router + engine handles: least-loaded healthy
                 dispatch, retry-on-other-engine (streams: only
                 before the first byte), Backoff quarantine /
                 readmission, router-level shedding
    session.py   StreamSession + SessionManager: the durable decode
                 session journal behind mid-stream failover — every
                 emitted token recorded with an absolute sequence
                 number, resume-as-prefill on a same-fingerprint
                 sibling, at-most-once splice, idle-watchdog and
                 drain-kick triggers, singa_stream_* counters
    sessionlog.py  SessionWal + ControlStateStore: the crash-safe
                 control plane — append-only per-epoch session WAL
                 (group commit, CRC per record, torn-tail-tolerant
                 replay), atomically-snapshotted control state,
                 epoch claim/fence for restart and zero-downtime
                 handoff, singa_router_wal_* counters
    fleet.py     EngineFleet + RolloutController + FleetServer:
                 N workers behind one router, canary rollout with
                 auto-rollback, streaming passthrough, elastic
                 grow/retire membership
    autoscale.py AutoScaler + AutoScaleSpec: SLO-driven control loop
                 over the windowed stats — grow on pressure (shed
                 rate, p95 vs budget, queue depth, occupancy), drain
                 and retire after a quiet streak, Backoff cooldown
    traffic.py   TrafficGen + Phase scenarios: open-loop Poisson
                 load (steady/ramp/flash_crowd/diurnal), long-tail
                 prompt mixes, QoS priority mixes, slow readers,
                 chaos hooks (incl. stall_chaos stragglers) —
                 offered vs completed, shed rate, p50/p95/p99 per
                 phase and per class
    qos.py       request-lifecycle QoS vocabulary: end-to-end
                 deadline propagation (absolute in-process, remaining
                 -ms on the wire), priority classes interactive /
                 batch / best_effort, RetryBudget token bucket,
                 per-(tenant, class) Retry-After backoffs
    tenancy.py   TenantRegistry + TenantSpec + TenantBudget: per-
                 tenant QoS envelopes — retry-budget floors, queue/
                 slot/KV quotas, brownout overrides — with unknown
                 tenant ids folded into one bounded `other` envelope
                 (blast-radius containment for the multi-tenant
                 fleet)
    wire.py      zero-copy binary transport: length-prefixed framed
                 protocol over persistent sockets (BinaryEngineHandle
                 / BinaryTransportServer, multiplexed in-flight
                 requests), shared-memory TokenRing for the in-
                 process hop, batched token flushes (flush_tokens/
                 flush_ms) on both wire surfaces, per-engine
                 negotiation with automatic HTTP fallback
                 (NegotiatingEngineHandle), singa_wire_* counters
                 with a serialization-time split — HTTP/JSON stays
                 the always-on debug surface

Fault sites `serve.admit` / `serve.batch` / `serve.reload` /
`fleet.dispatch` / `fleet.rollout` / `scale.decide` / `serve.hedge` /
`engine.stall` / `serve.resume` (utils.faults) make every degradation
path — hedged tail-cutting and mid-stream failover included —
deterministic on CPU.
"""

from . import qos
from .autoscale import AutoScaler, AutoScaleSpec
from .batcher import (Cancelled, DeadlineExpired, MicroBatcher,
                      Overloaded, Ticket)
from .engine import InferenceEngine, ServeSpec
from .fleet import (EngineFleet, FleetServer, RolloutController,
                    RolloutSpec)
from .kvcache import PagedKVCache
from .router import (EngineUnavailable, HttpEngineHandle, LameDuck,
                     LocalEngineHandle, Router, RouterSpec,
                     RouterStats, UnknownSession)
from .scheduler import ContinuousScheduler, StreamTicket
from .server import InferenceServer
from .session import SessionManager, StreamSession, StreamStats
from .sessionlog import (ControlStateStore, SessionWal, WalStats,
                         replay_wal, reduce_sessions, walcheck)
from .router import UnknownModel
from .stats import ServeStats
from .qos import PRIORITIES, ClassBackoffs, RetryBudget
from .tenancy import (TenantBudget, TenantRegistry, TenantSpec)
from .traffic import (Phase, TrafficGen, diurnal, flash_crowd,
                      kill_chaos, ramp, stall_chaos, steady)
from .wire import (BinaryEngineHandle, BinaryTransportServer,
                   NegotiatingEngineHandle, TokenRing, WireError,
                   WireStats, WireUnavailable)

__all__ = ["AutoScaler", "AutoScaleSpec", "BinaryEngineHandle",
           "BinaryTransportServer", "Cancelled",
           "ClassBackoffs", "ContinuousScheduler",
           "ControlStateStore", "DeadlineExpired",
           "EngineFleet", "EngineUnavailable", "FleetServer",
           "HttpEngineHandle", "InferenceEngine", "InferenceServer",
           "LameDuck", "LocalEngineHandle", "MicroBatcher",
           "NegotiatingEngineHandle",
           "Overloaded", "PRIORITIES", "PagedKVCache", "Phase",
           "RetryBudget", "RolloutController", "RolloutSpec",
           "Router", "RouterSpec", "RouterStats", "ServeSpec",
           "ServeStats", "SessionManager", "SessionWal",
           "StreamSession", "StreamStats", "StreamTicket",
           "TenantBudget", "TenantRegistry", "TenantSpec", "Ticket",
           "TokenRing", "TrafficGen", "UnknownModel",
           "UnknownSession", "WalStats", "WireError", "WireStats",
           "WireUnavailable",
           "diurnal", "flash_crowd", "kill_chaos", "qos", "ramp",
           "reduce_sessions", "replay_wal", "stall_chaos", "steady",
           "walcheck"]
