"""Deadline-aware micro-batcher: coalesce queued requests into the
smallest admissible compiled bucket.

Admission (`submit`) is bounded-queue with `Backoff`-based shedding:
a full queue (or an injected `serve.admit` fault) raises `Overloaded`
carrying a `retry_after` hint that grows exponentially with
consecutive sheds — callers that honor it decongest the queue instead
of hammering it.  Admitted requests get a `Ticket` (a tiny future);
`Ticket.wait()` returns the result dict or raises the failure.

The dispatch loop gathers the queue head, waits at most
`batch_window_s` for co-batchable arrivals (early-out when the widest
bucket fills), drops requests whose deadline passed while queued
(counted `expired`, failed with `DeadlineExpired`), picks
`spec.bucket_for(n, max_plen)` and LEFT-pads every prompt to the
bucket length (`plens` carries the real lengths for the engine's
kmask).  Overflow beyond the bucket's batch goes back to the queue
head.  Pad rows are dummy single-pad-token prompts — they decode
garbage nobody reads; occupancy (real/slots) is the stat that prices
them.

Fault sites: `serve.admit` (shed one request), `serve.batch` (fail
one dispatched batch's requests — the loop and the server stay up).
Params atomicity: the loop reads `engine.params` ONCE per batch and
passes it to `run_batch`, so a hot-reload swap mid-dispatch cannot
tear a batch (see engine.py).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..utils import faults
from . import qos
from .engine import InferenceEngine
from .stats import ServeStats
from .tenancy import TenantRegistry


class Overloaded(RuntimeError):
    """Admission rejected; retry after `retry_after` seconds."""

    def __init__(self, msg: str, retry_after: float = 0.0):
        super().__init__(msg)
        self.retry_after = retry_after


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before it was dispatched.  With
    end-to-end propagation (serve/qos.py) this includes dead on
    arrival: the remaining budget was already <= 0 at admission."""


class Cancelled(RuntimeError):
    """The caller cancelled the request (a hedge's losing attempt):
    dropped from the queue / retired from its slot, counted
    `cancelled` — never `failed`, never a strike."""


class Ticket:
    """One request's future: wait() blocks until the dispatch loop
    resolves or fails it."""

    def __init__(self):
        self._done = threading.Event()
        self._result: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def _resolve(self, result: Dict[str, Any]) -> None:
        self._result = result
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError("request still queued/running")
        if self._error is not None:
            raise self._error
        return self._result


@dataclass
class _Request:
    tokens: np.ndarray            # (plen,) int32
    plen: int
    mode: str
    ticket: Ticket
    t_submit: float
    deadline: Optional[float]     # monotonic, None = no deadline
    priority: str = "interactive"
    tenant: str = "default"       # registry-folded tenant label
    cancel_event: Optional[threading.Event] = None
    extra: Dict[str, Any] = field(default_factory=dict)


class MicroBatcher:
    """See module docstring.  One daemon dispatch thread; `submit` is
    called from any number of frontend threads."""

    def __init__(self, engine: InferenceEngine,
                 stats: Optional[ServeStats] = None, log_fn=print,
                 backoff: Optional[faults.Backoff] = None,
                 tenancy: Optional[TenantRegistry] = None):
        self.engine = engine
        self.spec = engine.spec
        self.stats = stats if stats is not None else engine.stats
        self.log = log_fn
        # per-tenant queue quotas + brownout overrides (an
        # unconfigured registry is all-default: no quota, engine
        # fractions — exact legacy admission)
        self.tenancy = tenancy or TenantRegistry()
        self._backoff = backoff if backoff is not None else \
            faults.Backoff(base=0.05, cap=2.0, seed=self.spec.seed)
        self._q: deque = deque()
        self._cv = threading.Condition()
        # correlation ids: req-N assigned at admission, batch-M at
        # dispatch; the dispatch span lists its requests' corrs, and
        # engine spans open inside it — request→batch→engine is one
        # traceable flow (docs/OBSERVABILITY.md)
        self._req_ids = itertools.count(1)
        self._batch_ids = itertools.count(1)
        # per-class shed streaks/backoffs (honest per-class
        # Retry-After; the interactive stream matches the old
        # single-class behavior bit-for-bit)
        self._class_backoffs = qos.ClassBackoffs(
            base=getattr(self._backoff, "base", 0.05),
            cap=getattr(self._backoff, "cap", 2.0),
            seed=getattr(self._backoff, "seed", self.spec.seed))
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            return self
        self._stop = False
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-dispatch",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # fail anything still queued so no client blocks forever
        with self._cv:
            leftovers = list(self._q)
            self._q.clear()
            self.stats.gauge("queue_depth", 0)
        for r in leftovers:
            self.stats.count("failed")
            r.ticket._fail(RuntimeError("server shutting down"))

    # -- admission ----------------------------------------------------------
    def submit(self, tokens, mode: str = "generate",
               timeout: Optional[float] = None,
               deadline: Optional[float] = None,
               priority: str = "interactive",
               cancel_event: Optional[threading.Event] = None,
               tenant: Optional[str] = None) -> Ticket:
        """Admit one request.  `tokens` is a 1-D int32 prompt;
        `deadline` (absolute monotonic; wins over `timeout`, which
        still derives one: spec.request_timeout_s default, <=0 = none)
        bounds time-in-queue — a request dead on arrival is refused
        before it queues (`expired_on_arrival`).  `priority`
        (serve/qos.py classes) drives brownout: under queue pressure
        lower classes shed first with an honest per-class Retry-After.
        `tenant` (folded through the registry; None = `default`)
        enforces the tenant's queue quota and scopes its Retry-After
        streak — one tenant filling its quota sheds ITS overflow, not
        a neighbor's traffic.  `cancel_event`, when set by the caller,
        drops the request at the next gather (counted `cancelled`).
        Raises `Overloaded` (with `retry_after`) on shed; ValueError
        for an unservable prompt or unknown priority."""
        arr = np.asarray(tokens, np.int32).reshape(-1)
        if arr.size < 1:
            self.stats.count("rejected")
            raise ValueError("empty prompt")
        if arr.size > self.spec.max_prompt_len:
            # fail fast at admission (the HTTP layer's 400): an
            # unservable prompt must not sit in the queue until its
            # deadline turns it into a 504
            self.stats.count("rejected")
            raise ValueError(
                f"prompt length {arr.size} exceeds the largest bucket "
                f"({self.spec.max_prompt_len}); not servable")
        if mode not in ("generate", "predict"):
            self.stats.count("rejected")
            raise ValueError(f"unknown mode {mode!r}")
        try:
            priority = qos.check_priority(priority)
        except ValueError:
            self.stats.count("rejected")
            raise
        tenant = self.tenancy.label(tenant)
        deadline = qos.resolve_deadline(timeout, deadline,
                                        self.spec.request_timeout_s)
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            # dead on arrival: refuse before it queues — zero queue
            # time, zero engine work burned on a client that gave up
            self.stats.count("expired_on_arrival")
            raise DeadlineExpired(
                f"dead on arrival: deadline passed "
                f"{now - deadline:.3f}s before admission")
        corr = f"req-{next(self._req_ids)}"
        req = _Request(tokens=arr, plen=int(arr.size), mode=mode,
                       ticket=Ticket(), t_submit=now,
                       deadline=deadline, priority=priority,
                       tenant=tenant, cancel_event=cancel_event,
                       extra={"corr": corr})
        with obs.span("batcher.admit", corr=corr, mode=mode,
                      plen=int(arr.size), priority=priority,
                      tenant=tenant):
            try:
                faults.maybe_fault("serve.admit")
            except faults.FaultError as e:
                return self._shed(f"admission fault: {e}", corr=corr,
                                  priority=priority, tenant=tenant)
            quota = self.tenancy.queue_quota(
                tenant, self.spec.queue_capacity)
            with self._cv:
                if self._stop:
                    raise RuntimeError("batcher is stopped")
                depth = len(self._q)
                tdepth = sum(1 for r in self._q if r.tenant == tenant)
                if depth >= self.spec.queue_capacity or \
                        tdepth >= quota or \
                        not self._brownout_admits(priority, depth,
                                                  tenant):
                    pass  # shed outside the lock's happy path below
                else:
                    self._q.append(req)
                    self._class_backoffs.reset(priority, tenant=tenant)
                    self.stats.count("submitted")
                    self.stats.tenants.count("submitted", tenant)
                    self.stats.gauge("queue_depth", len(self._q))
                    self._cv.notify()
                    return req.ticket
            if depth >= self.spec.queue_capacity:
                why = f"queue full ({self.spec.queue_capacity} requests)"
            elif tdepth >= quota:
                why = (f"tenant {tenant} queue quota full "
                       f"({tdepth}/{quota} of "
                       f"{self.spec.queue_capacity})")
            else:
                why = (f"brownout: queue {depth}/"
                       f"{self.spec.queue_capacity} sheds {priority}")
            return self._shed(why, corr=corr, priority=priority,
                              tenant=tenant)

    def _brownout_admits(self, priority: str, depth: int,
                         tenant: str = "default") -> bool:
        """Class-aware admission under pressure: best_effort is shed
        once the queue is `brownout_be_frac` full, batch at
        `brownout_batch_frac`; interactive rides to the cap.  A tenant
        with configured brownout overrides uses its own fractions."""
        if priority == "interactive":
            return True
        be_frac, batch_frac = self.tenancy.brownout_fracs(
            tenant, self.spec.brownout_be_frac,
            self.spec.brownout_batch_frac)
        frac = be_frac if priority == "best_effort" else batch_frac
        return depth < max(int(frac * self.spec.queue_capacity), 1)

    def _shed(self, why: str, corr: Optional[str] = None,
              priority: str = "interactive",
              tenant: str = "default") -> "Ticket":
        self.stats.count("shed")
        self.stats.count(f"shed_{priority}")
        self.stats.tenants.count("shed", tenant)
        retry = self._class_backoffs.shed_delay(priority,
                                                tenant=tenant)
        obs.emit_event("serve.shed", why=why, corr=corr,
                       priority=priority, tenant=tenant,
                       retry_after=round(retry, 4))
        raise Overloaded(f"request shed ({why}); retry after "
                         f"{retry:.3f}s", retry_after=retry)

    # -- dispatch loop ------------------------------------------------------
    def _loop(self) -> None:
        while True:
            gathered = self._gather()
            if gathered is None:
                if self._stop:
                    return
                continue
            reqs, bucket = gathered
            self._dispatch(reqs, bucket)

    def _gather(self) -> Optional[Tuple[List[_Request],
                                        Tuple[int, int]]]:
        """Block for work, coalesce within the batch window, expire
        stale requests, choose a bucket, and push overflow back."""
        spec = self.spec
        with self._cv:
            while not self._q and not self._stop:
                self._cv.wait(0.1)
            if not self._q:
                return None
            t_end = time.monotonic() + spec.batch_window_s
            while len(self._q) < spec.max_batch and not self._stop:
                rem = t_end - time.monotonic()
                if rem <= 0:
                    break
                self._cv.wait(rem)
            # take same-mode requests from the head; different-mode
            # ones go back to the head (they lead the next gather)
            mode = self._q[0].mode
            reqs: List[_Request] = []
            defer: List[_Request] = []
            now = time.monotonic()
            while self._q and len(reqs) < spec.max_batch:
                r = self._q.popleft()
                if r.cancel_event is not None and \
                        r.cancel_event.is_set():
                    # hedge loser: dropped before any engine work
                    self.stats.count("cancelled")
                    r.ticket._fail(Cancelled(
                        "cancelled by caller while queued"))
                    continue
                if r.deadline is not None and now > r.deadline:
                    self.stats.count("expired")
                    r.ticket._fail(DeadlineExpired(
                        f"deadline passed after "
                        f"{now - r.t_submit:.3f}s in queue"))
                    continue
                if r.mode != mode:
                    defer.append(r)
                    continue
                reqs.append(r)
            if not reqs:
                self._q.extendleft(reversed(defer))
                self.stats.gauge("queue_depth", len(self._q))
                return None
            bucket = spec.bucket_for(len(reqs),
                                     max(r.plen for r in reqs))
            if len(reqs) > bucket[0]:
                defer = reqs[bucket[0]:] + defer
                reqs = reqs[:bucket[0]]
            self._q.extendleft(reversed(defer))
            self.stats.gauge("queue_depth", len(self._q))
        return reqs, bucket

    def _dispatch(self, reqs: List[_Request],
                  bucket: Tuple[int, int]) -> None:
        b, p = bucket
        corr = f"batch-{next(self._batch_ids)}"
        with obs.span("batcher.dispatch", corr=corr, batch=b, plen=p,
                      reqs=[r.extra.get("corr") for r in reqs]):
            self._dispatch_batch(reqs, bucket)

    def _dispatch_batch(self, reqs: List[_Request],
                        bucket: Tuple[int, int]) -> None:
        b, p = bucket
        t_disp = time.monotonic()
        try:
            faults.maybe_fault("serve.batch")
            # ONE read of the live tree: a concurrent hot-reload swap
            # cannot change params under this batch
            params = self.engine.params
            step = self.engine.params_step
            tokens = np.full((b, p), self.spec.pad_id, np.int32)
            plens = np.ones((b,), np.int32)   # pad rows: 1-token dummy
            for i, r in enumerate(reqs):
                tokens[i, p - r.plen:] = r.tokens
                plens[i] = r.plen
            mode = reqs[0].mode
            out = self.engine.run_batch(mode, tokens, plens,
                                        params=params)
        except Exception as e:  # noqa: BLE001 — fail batch, keep serving
            self.stats.count("failed", len(reqs))
            # one more strike toward the degraded /healthz verdict
            # (reset by observe_batch on the next successful dispatch)
            self.stats.observe_batch_failure()
            self.log(f"warning: serve batch failed "
                     f"({type(e).__name__}: {e}); {len(reqs)} "
                     f"request(s) failed, server continues")
            for r in reqs:
                r.ticket._fail(e if isinstance(e, faults.FaultError)
                               else RuntimeError(f"batch failed: {e}"))
            return
        self.stats.observe_batch(len(reqs), b)
        now = time.monotonic()
        for i, r in enumerate(reqs):
            if r.mode == "generate":
                toks = self._trim_eos(out[i])
                result = {"tokens": toks, "step": step,
                          "bucket": [b, p]}
                ntok = len(toks)
            else:
                result = {"logprobs": out[i].tolist(), "step": step,
                          "bucket": [b, p]}
                ntok = 0
            self.stats.observe_latency(now - r.t_submit)
            self.stats.tenants.count("completed", r.tenant)
            self.stats.tenants.observe_latency(now - r.t_submit,
                                               r.tenant)
            # queue-wait = submit -> this dispatch; service = the
            # batch's device time (shared across its requests)
            self.stats.observe_request(t_disp - r.t_submit,
                                       now - t_disp, ntok)
            r.ticket._resolve(result)

    def _trim_eos(self, row: np.ndarray) -> List[int]:
        eos = self.spec.eos_id
        toks = row.tolist()
        if eos is None or eos not in toks:
            return toks
        return toks[:toks.index(eos) + 1]
