"""Continuous-batching scheduler: per-slot admission into a running
decode batch over the paged KV cache.

The static MicroBatcher ties a request's fate to its batch: the
compiled bucket program decodes all `max_new_tokens` for every row,
so one long generation holds every co-batched short request hostage
(BENCH_pr5's p50 7.6 ms vs p95 108.8 ms is exactly that head-of-line
gap).  Here a request occupies one of `cb_slots` SLOTS instead:

  admit    a free slot at ANY decode step — reserve its worst-case
           blocks (ceil((plen + max_new) / block_len), so pool
           exhaustion is an admission decision, never a mid-decode
           OOM), run the ONE compiled prefill program into them, and
           join the running batch on the next step;
  step     the ONE compiled fixed-slot-count decode program advances
           every active slot a token; inactive slots ride along
           pointing at the null block (garbage out, masked, ignored);
  retire   on EOS / max-new / deadline the slot's blocks return to
           the free pool immediately and the slot is free for the
           next admission that very step.

Control plane vs data plane ("RPC Considered Harmful"): everything in
this file is host-side numpy bookkeeping; device work is exactly one
compiled-program invocation per prefill and one per decode step, both
AOT-compiled at warmup with (slots, blocks-per-slot, block_len, pool
size) as the only geometry — zero recompiles after warmup, same
guarantee as the bucket path.

Params atomicity: the loop reads `engine.params` ONCE per iteration
and threads it through that iteration's prefills and decode step, so
a hot-reload swap can never tear a step.  A stream that spans a
reload finishes on the new params from the next step on — each step
is internally consistent, which is the no-tear guarantee the static
path makes per batch.

Admission is strict FIFO: when the queue head cannot get a slot or
its blocks, nothing behind it jumps ahead (no starvation of long
prompts).  Shedding (`Overloaded` + Backoff retry_after) happens only
when the pending queue itself is full — the same story as the
MicroBatcher, with the block pool as the second bounded resource.

Fault sites: `serve.admit` (shed one submission), `serve.batch` (fail
one decode step — its active requests fail, the loop and server stay
up, `consecutive_batch_failures` moves toward the degraded verdict).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import obs
from ..utils import faults
from . import qos
from .batcher import Cancelled, DeadlineExpired, Overloaded
from .engine import InferenceEngine
from .kvcache import PagedKVCache
from .stats import ServeStats
from .tenancy import TenantRegistry


class StreamTicket:
    """One request's future, streaming edition: tokens are observable
    as they are produced (`events()` / `tokens()`), and `wait()`
    blocks for the final result dict exactly like `Ticket.wait`."""

    def __init__(self, corr: Optional[str] = None,
                 first_index: int = 0):
        self.corr = corr
        # absolute sequence number of the FIRST token this ticket will
        # emit: 0 for a fresh stream, `resume_from` for a failover
        # re-admission — the k-th emitted token is index
        # first_index + k, so both legs of a spliced stream number
        # consistently and the router can dedupe by index
        self.first_index = int(first_index)
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._result: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    # -- producer side (scheduler thread) -----------------------------------
    def _emit(self, token: int) -> None:
        self._q.put(("tok", int(token)))

    def _resolve(self, result: Dict[str, Any]) -> None:
        self._result = result
        self._done.set()
        self._q.put(("done", result))

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._done.set()
        self._q.put(("err", exc))

    # -- consumer side ------------------------------------------------------
    def events(self, timeout: Optional[float] = None):
        """Yield ("tok", int) per produced token, then one ("done",
        result).  Raises the failure; raises TimeoutError when no
        event arrives within `timeout` seconds."""
        while True:
            try:
                kind, payload = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError("stream stalled") from None
            if kind == "err":
                raise payload
            yield kind, payload
            if kind == "done":
                return

    def tokens(self, timeout: Optional[float] = None):
        """Yield produced token ids; returns at end-of-stream."""
        for kind, payload in self.events(timeout=timeout):
            if kind == "tok":
                yield payload

    def drain_events(self, max_n: int = 1,
                     timeout: Optional[float] = None,
                     linger_s: float = 0.0):
        """Batched drain for the flushed transports (serve/wire.py):
        block up to `timeout` for the FIRST event, then greedily take
        whatever is already queued — lingering at most `linger_s` for
        stragglers — up to `max_n` events per call.  One queue wakeup
        amortizes over the whole batch instead of one lock round-trip
        per token.  Returns a list of (kind, payload) tuples ending
        early at any non-"tok" event; raises the stream's failure and
        TimeoutError exactly like `events()`.  `max_n=1, linger_s=0`
        reproduces the unbatched behavior bit-for-bit."""
        try:
            evs = [self._q.get(timeout=timeout)]
        except queue.Empty:
            raise TimeoutError("stream stalled") from None
        if evs[0][0] == "err":
            raise evs[0][1]
        limit = max(int(max_n), 1)
        wait_until = (time.monotonic() + max(float(linger_s), 0.0)
                      if linger_s and linger_s > 0 else None)
        while len(evs) < limit and evs[-1][0] == "tok":
            try:
                if wait_until is None:
                    ev = self._q.get_nowait()
                else:
                    rem = wait_until - time.monotonic()
                    if rem <= 0:
                        ev = self._q.get_nowait()
                    else:
                        ev = self._q.get(timeout=rem)
            except queue.Empty:
                break
            if ev[0] == "err":
                # surface the failure only after the caller has
                # consumed the tokens drained before it: a mid-batch
                # error must not eat already-produced tokens
                evs.append(("failed", ev[1]))
                break
            evs.append(ev)
        return evs

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError("request still queued/running")
        if self._error is not None:
            raise self._error
        return self._result


@dataclass
class _CBRequest:
    tokens: np.ndarray            # (plen,) int32
    plen: int
    max_new: int
    nblocks: int                  # conservative reservation
    ticket: StreamTicket
    t_submit: float
    deadline: Optional[float]
    corr: str
    priority: str = "interactive"
    tenant: str = "default"
    cancel_event: Optional[threading.Event] = None
    t_admit: float = 0.0
    produced: List[int] = field(default_factory=list)
    # trace context captured at submit — the prefill runs on the
    # scheduler loop thread, so its span needs an explicit anchor to
    # land in the submitting request's trace
    link: Any = None


class ContinuousScheduler:
    """See module docstring.  One daemon loop thread; `submit` is
    called from any number of frontend threads."""

    def __init__(self, engine: InferenceEngine,
                 stats: Optional[ServeStats] = None, log_fn=print,
                 backoff: Optional[faults.Backoff] = None,
                 tenancy: Optional[TenantRegistry] = None):
        if not engine.spec.cb_on:
            raise ValueError("ContinuousScheduler needs a cb=on "
                             "ServeSpec")
        self.engine = engine
        self.spec = engine.spec
        self.stats = stats if stats is not None else engine.stats
        self.log = log_fn
        self._backoff = backoff if backoff is not None else \
            faults.Backoff(base=0.05, cap=2.0, seed=self.spec.seed)
        self.tenancy = tenancy if tenancy is not None \
            else TenantRegistry()
        self.kv: Optional[PagedKVCache] = None
        self._pending: deque = deque()
        self._cv = threading.Condition()
        self._req_ids = itertools.count(1)
        # per-class shed streaks/backoffs (see serve/qos.py); the
        # interactive stream matches the old single-class behavior
        self._class_backoffs = qos.ClassBackoffs(
            base=getattr(self._backoff, "base", 0.05),
            cap=getattr(self._backoff, "cap", 2.0),
            seed=getattr(self._backoff, "seed", self.spec.seed))
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # slot state (numpy, scheduler-thread-owned)
        s = self.spec.cb_slots
        self._active = np.zeros((s,), bool)
        self._ntoks = np.zeros((s,), np.int32)
        self._last = np.zeros((s,), np.int32)
        self._slot_req: List[Optional[_CBRequest]] = [None] * s

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ContinuousScheduler":
        if self._thread is not None:
            return self
        if self.engine.params is None:
            raise RuntimeError("engine has no params; call load()")
        spec = self.spec
        if spec.cb_pool_blocks - 1 < spec.cb_blocks_per_slot:
            # a pool that cannot hold even one worst-case request
            # would wedge every admission; refuse loudly at startup
            raise ValueError(
                f"cb_blocks={spec.cb_pool_blocks} cannot hold one "
                f"worst-case request ({spec.cb_blocks_per_slot} "
                f"blocks + null)")
        if self.kv is None:
            import jax
            dtype = jax.tree_util.tree_leaves(self.engine.params)[0].dtype
            self.kv = PagedKVCache(
                self.engine.net, num_slots=spec.cb_slots,
                max_blocks_per_slot=spec.cb_blocks_per_slot,
                num_blocks=spec.cb_pool_blocks,
                block_len=spec.cb_block_len, dtype=dtype)
            self.stats.gauge("cb_slot_capacity", spec.cb_slots)
            self.stats.gauge("cb_blocks_total", self.kv.usable_blocks)
            # MemoryWatch: the pools just allocated, from the same
            # block geometry init_pools used (analytic == actual here)
            from ..obs import perf
            from .kvcache import pool_bytes
            perf.set_memory(
                "kv_pool",
                pool_bytes(self.engine.net, spec.cb_pool_blocks,
                           spec.cb_block_len, dtype),
                scope=getattr(self.engine, "_perf_scope", "scheduler"))
        self._stop = False
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-cb", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        with self._cv:
            leftovers = list(self._pending)
            self._pending.clear()
            self.stats.gauge("queue_depth", 0)
        for r in leftovers:
            self.stats.count("failed")
            r.ticket._fail(RuntimeError("server shutting down"))
        for s, r in enumerate(self._slot_req):
            if r is not None:
                self._retire(s, "shutdown", self.engine.params_step)

    # -- admission ----------------------------------------------------------
    def submit(self, tokens, timeout: Optional[float] = None,
               max_new: Optional[int] = None,
               deadline: Optional[float] = None,
               priority: str = "interactive",
               tenant: Optional[str] = None,
               cancel_event: Optional[threading.Event] = None,
               resume_from: int = 0) -> StreamTicket:
        """Admit one generate request.  `max_new` caps this request's
        generation (clamped to spec.max_new_tokens).  `deadline`
        (absolute monotonic; wins over `timeout`) is the request's
        end-to-end budget — dead on arrival is refused before any
        queue or engine work (`expired_on_arrival`); `priority` drives
        brownout admission; a set `cancel_event` drops the request at
        the next scheduler touch (queued or mid-decode, counted
        `cancelled`).  Raises ValueError for a never-servable prompt
        or unknown priority (fail fast, the HTTP layer's 400),
        `Overloaded` when the pending queue is full or brownout sheds
        this class.

        `resume_from=n` re-admits a failed-over stream: `tokens` is
        (original prompt ‖ the n tokens already emitted), the fresh
        prefill re-derives the continuation (greedy decode is
        bit-deterministic given fingerprint + prefix, the PR 8 parity
        property), and the ticket numbers its output from absolute
        index n so the router can splice and dedupe.  Only
        max_new - n MORE tokens are generated and the block
        reservation covers exactly (grown prompt + remainder).  A
        resume past `max_new` or past an already-emitted EOS is a
        fast 400 (counted `rejected`, zero engine steps) — the
        original stream was already complete."""
        spec = self.spec
        tenant = self.tenancy.label(tenant)
        arr = np.asarray(tokens, np.int32).reshape(-1)
        if arr.size < 1:
            self.stats.count("rejected")
            raise ValueError("empty prompt")
        if arr.size > spec.cb_max_prompt_len:
            self.stats.count("rejected")
            raise ValueError(
                f"prompt length {arr.size} exceeds the cb prompt cap "
                f"({spec.cb_max_prompt_len}); not servable")
        mn = int(max_new) if max_new is not None else \
            int(spec.max_new_tokens)
        if mn < 1:
            self.stats.count("rejected")
            raise ValueError(f"max_new must be >= 1, got {mn}")
        mn = min(mn, int(spec.max_new_tokens))
        resume_from = int(resume_from)
        if resume_from < 0:
            self.stats.count("rejected")
            raise ValueError(f"resume_from must be >= 0, got "
                             f"{resume_from}")
        if resume_from > 0:
            if resume_from >= mn:
                self.stats.count("rejected")
                raise ValueError(
                    f"resume_from {resume_from} is past max_new {mn}; "
                    f"the stream already completed")
            if resume_from > arr.size:
                self.stats.count("rejected")
                raise ValueError(
                    f"resume_from {resume_from} exceeds the "
                    f"{arr.size}-token prompt+prefix")
            if spec.eos_id is not None and \
                    np.any(arr[-resume_from:] == int(spec.eos_id)):
                self.stats.count("rejected")
                raise ValueError(
                    f"resume_from {resume_from} is past EOS: the "
                    f"emitted prefix already contains eos_id "
                    f"{spec.eos_id}")
            mn = mn - resume_from     # only the remainder decodes
            self.stats.count("resumed")
        nblocks = -(-(int(arr.size) + mn) // int(spec.cb_block_len))
        deadline = qos.resolve_deadline(timeout, deadline,
                                        spec.request_timeout_s)
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            # dead on arrival: refuse before it queues — zero engine
            # steps burned on a client that already gave up
            self.stats.count("expired_on_arrival")
            raise DeadlineExpired(
                f"dead on arrival: deadline passed "
                f"{now - deadline:.3f}s before admission")
        # inherit the caller's correlation chain when one is open on
        # this thread (the HTTP handler's serve.request span) instead
        # of unconditionally minting a fresh cbreq-N — the old mint
        # silently severed router→scheduler correlation on every hop
        corr = obs.current_corr() or f"cbreq-{next(self._req_ids)}"
        link = obs.trace_context()
        req = _CBRequest(tokens=arr, plen=int(arr.size), max_new=mn,
                         nblocks=nblocks,
                         ticket=StreamTicket(corr,
                                             first_index=resume_from),
                         t_submit=now, deadline=deadline, corr=corr,
                         priority=priority, tenant=tenant,
                         cancel_event=cancel_event, link=link)
        quota = self.tenancy.queue_quota(tenant, spec.queue_capacity)
        with obs.span("scheduler.admit", corr=corr,
                      plen=int(arr.size), max_new=mn,
                      priority=priority, tenant=tenant):
            try:
                faults.maybe_fault("serve.admit")
            except faults.FaultError as e:
                self._shed(f"admission fault: {e}", corr=corr,
                           priority=priority, tenant=tenant)
            with self._cv:
                if self._stop:
                    raise RuntimeError("scheduler is stopped")
                depth = len(self._pending)
                tdepth = sum(1 for r in self._pending
                             if r.tenant == tenant)
                if depth >= spec.queue_capacity or \
                        tdepth >= quota or \
                        not self._brownout_admits(priority, depth,
                                                  tenant):
                    pass          # shed outside the happy path below
                else:
                    self._pending.append(req)
                    self._class_backoffs.reset(priority,
                                               tenant=tenant)
                    self.stats.count("submitted")
                    self.stats.tenants.count("submitted", tenant)
                    self.stats.gauge("queue_depth", len(self._pending))
                    self._cv.notify()
                    return req.ticket
            if depth >= spec.queue_capacity:
                why = f"queue full ({spec.queue_capacity} requests)"
            elif tdepth >= quota:
                why = (f"tenant {tenant} queue quota full "
                       f"({tdepth}/{quota} of {spec.queue_capacity})")
            else:
                why = (f"brownout: queue {depth}/"
                       f"{spec.queue_capacity} sheds {priority}")
            self._shed(why, corr=corr, priority=priority,
                       tenant=tenant)

    def _brownout_admits(self, priority: str, depth: int,
                         tenant: str = "default") -> bool:
        """Class-aware admission under pressure: best_effort is shed
        once the pending queue is `brownout_be_frac` full, batch at
        `brownout_batch_frac`; interactive rides to the cap.  A
        tenant's spec can tighten either fraction for ITS traffic."""
        if priority == "interactive":
            return True
        be, batch = self.tenancy.brownout_fracs(
            tenant, self.spec.brownout_be_frac,
            self.spec.brownout_batch_frac)
        frac = be if priority == "best_effort" else batch
        return depth < max(int(frac * self.spec.queue_capacity), 1)

    def _shed(self, why: str, corr: Optional[str] = None,
              priority: str = "interactive",
              tenant: str = "default") -> None:
        self.stats.count("shed")
        self.stats.count(f"shed_{priority}")
        self.stats.tenants.count("shed", tenant)
        retry = self._class_backoffs.shed_delay(priority,
                                                tenant=tenant)
        obs.emit_event("serve.shed", why=why, corr=corr,
                       priority=priority, tenant=tenant,
                       retry_after=round(retry, 4))
        raise Overloaded(f"request shed ({why}); retry after "
                         f"{retry:.3f}s", retry_after=retry)

    # -- the loop -----------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cv:
                while (not self._pending and not self._active.any()
                       and not self._stop):
                    self._cv.wait(0.05)
                if self._stop:
                    return
            self._iterate()

    def _iterate(self) -> None:
        """One scheduler step: expire, admit, decode, account."""
        # ONE params read covers this step's prefills AND decode — the
        # per-step no-tear guarantee (see module docstring)
        params = self.engine.params
        step_no = self.engine.params_step
        now = time.monotonic()
        self._expire_pending(now)
        try:
            self._admit_pending(params, step_no)
            if self._active.any():
                self._decode_step(params, step_no)
        except Exception as e:  # noqa: BLE001 — fail step, keep serving
            self._fail_step(e)
            return
        if self.kv is not None:
            self.stats.observe_cb_step(int(self._active.sum()),
                                       self.kv.blocks_in_use)
            self.stats.gauge("cb_blocks_in_use", self.kv.blocks_in_use)

    def _expire_pending(self, now: float) -> None:
        with self._cv:
            keep: deque = deque()
            expired: List[_CBRequest] = []
            cancelled: List[_CBRequest] = []
            for r in self._pending:
                if r.cancel_event is not None and \
                        r.cancel_event.is_set():
                    cancelled.append(r)
                elif r.deadline is not None and now > r.deadline:
                    expired.append(r)
                else:
                    keep.append(r)
            self._pending = keep
            self.stats.gauge("queue_depth", len(self._pending))
        for r in cancelled:
            self.stats.count("cancelled")
            r.ticket._fail(Cancelled(
                "cancelled by caller while queued"))
        for r in expired:
            self.stats.count("expired")
            r.ticket._fail(DeadlineExpired(
                f"deadline passed after {now - r.t_submit:.3f}s in "
                f"queue"))

    def _admit_pending(self, params, step_no: int) -> None:
        """Admit the queue head while a slot AND its blocks are free.
        FIFO with one tenancy carve-out: a head blocked ONLY by its
        own tenant's slot/KV quota is stepped over (its quota is its
        own blast radius — it must not wedge the other tenants), but
        a head blocked by a GLOBAL resource (block pool too empty)
        still holds everything behind it, preserving the
        no-starvation guarantee for long prompts."""
        spec = self.spec
        while True:
            free = np.flatnonzero(~self._active)
            with self._cv:
                if not self._pending or free.size == 0:
                    return
                # per-tenant occupancy among the ACTIVE slots (slot
                # count + conservative block reservations), once per
                # admission round
                slots_t: Dict[str, int] = {}
                blocks_t: Dict[str, int] = {}
                for r in self._slot_req:
                    if r is not None:
                        slots_t[r.tenant] = \
                            slots_t.get(r.tenant, 0) + 1
                        blocks_t[r.tenant] = \
                            blocks_t.get(r.tenant, 0) + r.nblocks
                req = None
                for i, cand in enumerate(self._pending):
                    if not self.kv.can_admit(cand.nblocks):
                        # global pool pressure: the effective head
                        # waits, nothing overtakes it
                        return
                    squota = self.tenancy.slot_quota(
                        cand.tenant, spec.cb_slots)
                    bquota = self.tenancy.kv_quota(
                        cand.tenant, self.kv.usable_blocks)
                    if slots_t.get(cand.tenant, 0) + 1 > squota or \
                            blocks_t.get(cand.tenant, 0) + \
                            cand.nblocks > bquota:
                        continue  # ITS quota, not ours: step over
                    req = cand
                    del self._pending[i]
                    break
                if req is None:
                    return        # every pending head is quota-held
                self.stats.gauge("queue_depth", len(self._pending))
            # last-instant guard AFTER the pop, BEFORE any blocks or
            # engine work: an engine never prefills a request that is
            # already dead or cancelled
            now = time.monotonic()
            if req.cancel_event is not None and \
                    req.cancel_event.is_set():
                self.stats.count("cancelled")
                req.ticket._fail(Cancelled(
                    "cancelled by caller before prefill"))
                continue
            if req.deadline is not None and now >= req.deadline:
                self.stats.count("expired")
                req.ticket._fail(DeadlineExpired(
                    f"deadline passed after {now - req.t_submit:.3f}s "
                    f"in queue"))
                continue
            slot = int(free[0])
            req.t_admit = now
            row = self.kv.alloc(slot, req.nblocks)
            toks = np.zeros((1, spec.cb_prefill_len), np.int32)
            toks[0, :req.plen] = req.tokens
            try:
                with obs.span("scheduler.prefill", corr=req.corr,
                              trace=req.link[0] if req.link else None,
                              parent=req.link[1] if req.link else None,
                              slot=slot, plen=req.plen):
                    tok0, self.kv.pools = self.engine.run_cb_prefill(
                        params, self.kv.pools, toks, req.plen,
                        row[:spec.cb_prefill_len // spec.cb_block_len])
            except Exception as e:  # noqa: BLE001 — fail req, keep going
                # the slot is not in _slot_req yet: clean it here so
                # the blocks cannot leak, fail only this request
                self.kv.free(slot)
                self.stats.count("failed")
                self.stats.observe_batch_failure()
                self.log(f"warning: cb prefill failed "
                         f"({type(e).__name__}: {e}); request "
                         f"{req.corr} failed, server continues")
                req.ticket._fail(RuntimeError(f"prefill failed: {e}"))
                return
            self._slot_req[slot] = req
            self._active[slot] = True
            self._ntoks[slot] = req.plen
            self._last[slot] = tok0
            req.produced.append(tok0)
            req.ticket._emit(tok0)
            self._maybe_retire(slot, tok0, step_no,
                               time.monotonic())

    def _decode_step(self, params, step_no: int) -> None:
        faults.maybe_fault("serve.batch")
        nxt, self.kv.pools = self.engine.run_cb_decode(
            params, self.kv.pools, self._last, self._ntoks,
            self.kv.table_array())
        now = time.monotonic()
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            self._ntoks[slot] += 1
            tok = int(nxt[slot])
            self._last[slot] = tok
            req = self._slot_req[slot]
            req.produced.append(tok)
            req.ticket._emit(tok)
            self._maybe_retire(slot, tok, step_no, now)

    def _maybe_retire(self, slot: int, tok: int, step_no: int,
                      now: float) -> None:
        req = self._slot_req[slot]
        eos = self.spec.eos_id
        if req.cancel_event is not None and req.cancel_event.is_set():
            # hedge loser mid-decode: free the slot THIS step — the
            # winner's fleet keeps the capacity, not a dead stream
            self._retire(slot, "cancelled", step_no)
        elif eos is not None and tok == eos:
            self._retire(slot, "eos", step_no)
        elif len(req.produced) >= req.max_new:
            self._retire(slot, "length", step_no)
        elif req.deadline is not None and now > req.deadline:
            self._retire(slot, "deadline", step_no)

    def _retire(self, slot: int, finish: str, step_no: int) -> None:
        req = self._slot_req[slot]
        self.kv.free(slot)
        self._active[slot] = False
        self._ntoks[slot] = 0
        self._last[slot] = 0
        self._slot_req[slot] = None
        now = time.monotonic()
        if finish == "shutdown":
            self.stats.count("failed")
            req.ticket._fail(RuntimeError("server shutting down"))
            return
        if finish == "cancelled":
            # not a completion, not a failure: no latency sample, no
            # strike — the caller asked for it (hedge loser)
            self.stats.count("cancelled")
            obs.emit_event("serve.cb_retire", corr=req.corr,
                           finish=finish, tokens=len(req.produced),
                           slot=slot)
            req.ticket._fail(Cancelled(
                "cancelled by caller mid-decode"))
            return
        self.stats.observe_latency(now - req.t_submit)
        self.stats.observe_request(req.t_admit - req.t_submit,
                                   now - req.t_admit,
                                   len(req.produced))
        self.stats.tenants.count("completed", req.tenant)
        self.stats.tenants.observe_latency(now - req.t_submit,
                                           req.tenant)
        obs.emit_event("serve.cb_retire", corr=req.corr,
                       finish=finish, tokens=len(req.produced),
                       slot=slot, tenant=req.tenant)
        req.ticket._resolve({"tokens": list(req.produced),
                             "step": step_no, "finish": finish,
                             "slots": self.spec.cb_slots})

    def _fail_step(self, e: BaseException) -> None:
        """A compiled call raised: fail every in-flight request, free
        everything, keep the loop alive (the batcher's degrade
        story)."""
        n = int(self._active.sum())
        self.stats.count("failed", n)
        self.stats.observe_batch_failure()
        self.log(f"warning: cb decode step failed "
                 f"({type(e).__name__}: {e}); {n} request(s) failed, "
                 f"server continues")
        err = (e if isinstance(e, faults.FaultError)
               else RuntimeError(f"decode step failed: {e}"))
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            req = self._slot_req[slot]
            self.kv.free(slot)
            self._active[slot] = False
            self._ntoks[slot] = 0
            self._last[slot] = 0
            self._slot_req[slot] = None
            req.ticket._fail(err)

    # -- reads --------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        out = {"pending": len(self._pending),
               "active_slots": int(self._active.sum()),
               "slots": self.spec.cb_slots}
        if self.kv is not None:
            out["kv"] = self.kv.snapshot()
        return out
