"""Fault-tolerant serving fleet: N engine workers behind a `Router`,
with rolling checkpoint rollout (canary → promote / auto-rollback).

`EngineFleet` spawns (in-process threads — the CPU-test and
single-machine shape) or adopts (subprocesses over HTTP, membership
from `parallel.bootstrap.parse_hostfile`) N engine workers, pins each
engine's fingerprint (no self-reload), and fronts them with a
`Router` (router.py: least-loaded healthy dispatch, quarantine/
readmission, retry-on-other-engine, router-level shedding).

The rollout state machine (`RolloutController`) closes the loop the
single-engine tier could not: a new checkpoint fingerprint is never
trusted fleet-wide.

    OBSERVE   poll `CheckpointManager.fingerprint()` (two stats, no
              reads).  A new latest step that is neither the pinned
              step nor an already-rejected fingerprint starts a
              canary.
    CANARY    exactly ONE engine (the least-loaded healthy one)
              reloads to the target step — deliberately WITHOUT the
              healthy-verdict walk-back: the canary exists to absorb
              the blast radius, so a DIVERGED or torn snapshot can
              never touch more than 1/N of traffic.  A reload that
              fails or lands elsewhere (torn target) is a counted
              refusal: the fleet never serves the fingerprint at all.
              While canarying: the canary dying / getting quarantined
              rolls back immediately (never a deadlock), and a NEWER
              fingerprint landing on disk aborts and restarts the
              canary on the newest step (stale canaries are wasted
              blast radius).
    PROMOTE   after `window_s` of canary traffic, promote fleet-wide
              only if the manifest health verdict is ok AND the
              canary's own health held AND its error rate and p95
              stayed within tolerance of the pre-canary window.
              Remaining engines reload one at a time (rolling — the
              fleet keeps serving throughout).
    ROLLBACK  any failed gate reloads the canary back to the pinned
              step and records the fingerprint as rejected (not
              re-canaried every poll; a new save changes it again).

Fault sites: `fleet.dispatch` (router attempt — behaves exactly like
an engine failure), `fleet.rollout` (controller tick — aborts the
rollout safely: rollback, never promote).  Events: `fleet.canary`,
`fleet.promote`, `fleet.rollback`, `fleet.quarantine`,
`fleet.readmit`, `fleet.join`, `fleet.retire`, `fleet.canary_abort`
(docs/OBSERVABILITY.md).

Membership is elastic (autoscale.py): `EngineFleet.grow()` spawns a
warmed, pinned worker and only then shows it to the Router;
`EngineFleet.retire(name, drain=True)` stops admissions, lets
in-flight work (including held stream slots) finish, then drops the
member.  A canary retired mid-rollout ABORTS the canary (counted as
`canary_aborts`, never a rollback) and the unjudged step re-canaries
on a survivor.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .. import obs
from ..utils import faults
from ..utils.checkpoint import CheckpointManager
from .engine import InferenceEngine, ServeSpec
from . import wire
from .router import (LameDuck, LocalEngineHandle, Router, RouterSpec,
                     HttpEngineHandle, UnknownSession, _handle_call)
from .server import InferenceServer
from .wire import NegotiatingEngineHandle
from .sessionlog import (ControlStateStore, SessionWal, WalStats,
                         claim_epoch, latest_wal_before, reduce_sessions,
                         replay_wal)
from .tenancy import TenantRegistry


@dataclass(frozen=True)
class RolloutSpec:
    """`--rollout_spec` grammar (ServeSpec mold): comma/semicolon-
    separated `key=value`."""
    poll_s: float = 0.25         # fingerprint poll cadence
    window_s: float = 1.0        # canary observation window
    min_requests: int = 0        # canary traffic wanted before verdict
    max_extends: int = 2         # extra windows waiting for traffic
    err_tolerance: float = 0.05  # canary err-rate − baseline bound
    p95_ratio: float = 3.0       # canary p95 / baseline p95 bound
    seed: int = 0

    def __post_init__(self):
        if float(self.poll_s) <= 0:
            raise ValueError(f"poll_s must be > 0, got {self.poll_s}")
        if float(self.window_s) <= 0:
            raise ValueError(f"window_s must be > 0, got "
                             f"{self.window_s}")
        if float(self.p95_ratio) <= 0:
            raise ValueError(f"p95_ratio must be > 0, got "
                             f"{self.p95_ratio}")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "RolloutSpec":
        kw: Dict[str, Any] = {}
        types = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key, sep, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or key not in types:
                    raise ValueError(f"unknown key {key!r}")
                kw[key] = (float(val) if "float" in str(types[key])
                           else int(val))
            except ValueError as e:
                raise ValueError(f"bad rollout spec entry {part!r} "
                                 f"(want key=value): {e}") from e
        return cls(**kw)


class RolloutController:
    """The OBSERVE→CANARY→PROMOTE/ROLLBACK state machine (module
    docstring).  One daemon thread ticks every `spec.poll_s`; every
    transition is counted, logged, and evented."""

    def __init__(self, router: Router, workspace: str,
                 spec: Optional[RolloutSpec] = None, log_fn=print,
                 family: Optional[str] = None):
        self.router = router
        self.spec = spec or RolloutSpec()
        self.log = log_fn
        # scope this controller to ONE checkpoint family: its canary
        # lands on a member of that family and promotion touches only
        # that family's members.  None = whole fleet (the legacy
        # single-family shape)
        self.family = family
        self.mgr = CheckpointManager(workspace, log_fn=lambda s: None)
        self.state = "OBSERVE"
        self.pinned_step: int = -1
        self.target_step: Optional[int] = None
        self.canary: Optional[str] = None       # engine name
        self._fp: Optional[tuple] = None
        self._rejected_fp: Optional[tuple] = None
        self._deadline: float = 0.0
        self._extends: int = 0
        self._pre: Dict[str, Any] = {}          # canary stats pre-reload
        self._baseline_p95: Optional[float] = None
        # outcome counters (fleet snapshot / BENCH_pr7.json)
        self.canaries = 0
        self.canary_restarts = 0
        self.promotions = 0
        self.rollbacks = 0
        self.refusals = 0
        self.canary_aborts = 0   # canary engine retired mid-canary
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self, pinned_step: int) -> "RolloutController":
        self.pinned_step = int(pinned_step)
        # deliberately NOT pre-capturing the fingerprint: a checkpoint
        # that landed between the engines loading and this start() would
        # otherwise be invisible forever (fingerprint unchanged from
        # here on, so OBSERVE never fires — the general form of the
        # fleet-pinned-at--1 startup race).  With _fp = None the first
        # tick always compares the latest step against the pinned one.
        self._fp = None
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-rollout",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(float(self.spec.poll_s)):
            self.tick()

    # -- one tick -----------------------------------------------------------
    def tick(self) -> None:
        """One state-machine step (also callable directly: tests and
        the bench drive rollout timing deterministically).  An
        injected `fleet.rollout` fault — or any unexpected controller
        error — aborts the rollout SAFELY: mid-canary it rolls back,
        and the fleet never promotes on a faulted tick."""
        with self._lock:
            try:
                faults.maybe_fault("fleet.rollout")
                if self.state == "OBSERVE":
                    self._tick_observe()
                elif self.state == "CANARY":
                    self._tick_canary()
            except Exception as e:  # noqa: BLE001 — degrade, never die
                self.log(f"warning: rollout tick failed "
                         f"({type(e).__name__}: {e})"
                         + ("; rolling canary back"
                            if self.state == "CANARY" else ""))
                if self.state == "CANARY":
                    self._rollback(f"rollout fault: {e}")

    def _tick_observe(self) -> None:
        fp = self.mgr.fingerprint()
        if fp == self._fp and self.target_step is None:
            return
        self._fp = fp
        if fp == self._rejected_fp:
            return                 # already judged and rolled back
        target = self.mgr.latest_step()
        if target is None or target == self.pinned_step:
            return
        self._begin_canary(target)

    def _begin_canary(self, target: int) -> None:
        name = self.router.pick_canary(family=self.family)
        if name is None:
            # no healthy engine to canary on — remember the target and
            # retry next tick rather than wedging
            self.target_step = target
            return
        self.target_step = target
        try:
            handle = self.router.handle_for(name)
        except KeyError:
            # picked engine retired between pick and use (autoscale
            # scale-down race) — remember the target, retry next tick
            return
        pre = self._engine_counts(handle)
        self._baseline_p95 = self.router.stats.latency_quantile(0.95)
        with obs.span("fleet.rollout", phase="canary", engine=name,
                      target=target) as fsp:
            try:
                # reload hop carries the rollout span's trace context
                # (_handle_call drops it for handles without the kwarg)
                got = _handle_call(
                    handle.reload, (),
                    {"step": target,
                     "trace": ((fsp.trace, fsp.span_id)
                               if fsp.trace else None)})
            except Exception as e:  # noqa: BLE001 — engine died on us
                got = {"outcome": "failed", "step": -1,
                       "error": str(e)}
        if got.get("outcome") not in ("reloaded", "unchanged") or \
                int(got.get("step", -1)) != target:
            # the target never made it onto ANY engine (failed/refused
            # reload, or a torn snapshot the restore walked back past)
            self.refusals += 1
            self._rejected_fp = self._fp
            self.target_step = None
            self.log(f"fleet: rollout to step {target} refused on "
                     f"canary {name} ({got.get('outcome')}, landed "
                     f"step {got.get('step')}); fleet stays on "
                     f"step {self.pinned_step}")
            obs.emit_event("fleet.rollback", engine=name,
                           target=target, why="canary reload refused",
                           outcome=str(got.get("outcome")))
            # belt and braces: make sure the canary still serves the
            # pinned params (a failed reload never unseats them, but a
            # walk-back may have landed elsewhere)
            self._restore_canary(name)
            return
        self.canaries += 1
        self.canary = name
        self.state = "CANARY"
        self._pre = pre
        self._deadline = time.monotonic() + float(self.spec.window_s)
        self._extends = 0
        self.log(f"fleet: canarying checkpoint step {target} on "
                 f"engine {name} (fleet pinned at "
                 f"{self.pinned_step})")
        obs.emit_event("fleet.canary", engine=name, target=target,
                       pinned=self.pinned_step)

    def _tick_canary(self) -> None:
        # newest-wins: a fresher fingerprint mid-canary restarts the
        # canary on the newest step (finishing a stale canary would
        # just delay the real rollout)
        fp = self.mgr.fingerprint()
        if fp != self._fp:
            self._fp = fp
            newest = self.mgr.latest_step()
            if newest is not None and newest != self.target_step and \
                    fp != self._rejected_fp:
                self.canary_restarts += 1
                name, old = self.canary, self.target_step
                self.log(f"fleet: newer checkpoint step {newest} "
                         f"landed mid-canary (was canarying {old}); "
                         f"restarting canary on the newest")
                self._restore_canary(name)
                self.state = "OBSERVE"
                self.canary = None
                self._begin_canary(newest)
                return
        mem = {m["name"]: m for m in self.router.members()}
        m = mem.get(self.canary)
        # canary deliberately retired (autoscale scale-down): the
        # checkpoint was never judged, so this is an ABORT, not a
        # rollback — the fingerprint stays eligible and re-canaries
        # on a surviving engine next tick
        if m is None or m.get("draining"):
            self._abort_canary("canary engine retired mid-canary")
            return
        # canary death / quarantine: roll back, never deadlock
        if m["quarantined"] or not m["healthy"]:
            self._rollback("canary engine died or degraded "
                           "mid-canary")
            return
        if time.monotonic() < self._deadline:
            return
        self._evaluate()

    def _engine_counts(self, handle) -> Dict[str, Any]:
        try:
            snap = handle.stats_snapshot()
        except Exception:  # noqa: BLE001 — dead engine: empty counts
            snap = {}
        return {"completed": int(snap.get("completed", 0)),
                "failed": int(snap.get("failed", 0)),
                "expired": int(snap.get("expired", 0))}

    def _evaluate(self) -> None:
        """The promotion gate: manifest verdict + canary health +
        error rate + p95, all against the pre-canary window."""
        name, target = self.canary, self.target_step
        try:
            handle = self.router.handle_for(name)
        except KeyError:
            self._abort_canary("canary engine retired at evaluation")
            return
        post = self._engine_counts(handle)
        served = post["completed"] - self._pre["completed"]
        if served < int(self.spec.min_requests) and \
                self._extends < int(self.spec.max_extends):
            # not enough canary traffic to judge yet — extend the
            # window a bounded number of times, then judge anyway
            self._extends += 1
            self._deadline = time.monotonic() + \
                float(self.spec.window_s)
            return
        reasons = []
        verdict = self.mgr.health_verdict(target)
        if verdict is not None and verdict != "ok":
            reasons.append(f"manifest health verdict {verdict!r}")
        mem = {m["name"]: m for m in self.router.members()}
        m = mem.get(name)
        if m is None or m["quarantined"] or not m["healthy"]:
            reasons.append("canary engine unhealthy at evaluation")
        errs = (post["failed"] - self._pre["failed"]) + \
            (post["expired"] - self._pre["expired"])
        err_rate = errs / max(served + errs, 1)
        if err_rate > float(self.spec.err_tolerance):
            reasons.append(f"canary error rate {err_rate:.3f} > "
                           f"{self.spec.err_tolerance}")
        try:
            snap = handle.stats_snapshot()
            p95 = snap.get("p95_latency_ms")
        except Exception:  # noqa: BLE001
            p95 = None
        if p95 is not None and self._baseline_p95 is not None:
            base_ms = self._baseline_p95 * 1e3
            if base_ms > 0 and p95 > base_ms * float(
                    self.spec.p95_ratio):
                reasons.append(f"canary p95 {p95:.1f}ms > "
                               f"{self.spec.p95_ratio}x baseline "
                               f"{base_ms:.1f}ms")
        if reasons:
            self._rollback("; ".join(reasons))
        else:
            self._promote(served)

    def _promote(self, served: int) -> None:
        name, target = self.canary, self.target_step
        failures = []
        with obs.span("fleet.rollout", phase="promote",
                      target=target) as fsp:
            for other in self.router.names():
                if other == name:
                    continue
                if self.family is not None and \
                        self.router.engine_family(other) != \
                        self.family:
                    continue       # another family's member: not ours
                try:
                    handle = self.router.handle_for(other)
                    got = _handle_call(
                        handle.reload, (),
                        {"step": target,
                         "trace": ((fsp.trace, fsp.span_id)
                                   if fsp.trace else None)})
                except KeyError:
                    continue           # retired mid-promote: skip
                except Exception as e:  # noqa: BLE001 — router will
                    got = {"outcome": "failed", "error": str(e)}
                if got.get("outcome") not in ("reloaded", "unchanged"):
                    # quarantine/degrade machinery picks this engine
                    # up; the rollout itself still promotes
                    failures.append((other, got.get("outcome")))
        self.promotions += 1
        self.pinned_step = target
        self._rejected_fp = None
        self._fp = self.mgr.fingerprint()
        self.state = "OBSERVE"
        self.canary = None
        self.target_step = None
        self.log(f"fleet: promoted checkpoint step {target} "
                 f"fleet-wide (canary {name} served {served} "
                 f"request(s))"
                 + (f"; reload failed on {failures}" if failures
                    else ""))
        obs.emit_event("fleet.promote", target=target, canary=name,
                       canary_served=served,
                       failed_members=[f[0] for f in failures])

    def _rollback(self, why: str) -> None:
        name, target = self.canary, self.target_step
        self._rejected_fp = self._fp
        self.state = "OBSERVE"
        self.canary = None
        self.target_step = None
        self.log(f"fleet: ROLLBACK of checkpoint step {target} "
                 f"(canary {name}): {why}; fleet stays on step "
                 f"{self.pinned_step}")
        self._restore_canary(name)
        # counted only once the canary is back on the pinned step (or
        # confirmed dead): `rollbacks` means "rollback COMPLETED", so
        # an observer never reads it while the bad step still serves
        self.rollbacks += 1
        obs.emit_event("fleet.rollback", engine=name, target=target,
                       why=why, pinned=self.pinned_step)

    def _abort_canary(self, why: str) -> None:
        """The canary engine was deliberately retired out from under
        the rollout.  The checkpoint was never judged, so nothing is
        rejected and no rollback is counted — clear the state and the
        remembered fingerprint so OBSERVE re-canaries the same step on
        a surviving engine next tick."""
        name, target = self.canary, self.target_step
        self.state = "OBSERVE"
        self.canary = None
        self.target_step = None
        self._fp = None            # force OBSERVE to re-compare
        self.canary_aborts += 1
        self.log(f"fleet: canary of step {target} ABORTED "
                 f"(engine {name}: {why}); step stays eligible and "
                 f"re-canaries on a surviving engine")
        self._restore_canary(name)  # best-effort; gone engine = no-op
        obs.emit_event("fleet.canary_abort", engine=name,
                       target=target, why=why)

    def _restore_canary(self, name: Optional[str]) -> None:
        """Put the (possibly dead) canary back on the pinned step —
        best-effort: a dead engine is already quarantined and will be
        re-pinned by readmission-time reload if needed.  A pinned step
        of -1 (cold start: nothing ever promoted) restores the canary
        to its fresh-init params via `reload(step=-1)` — without it a
        rejected FIRST checkpoint would keep serving on the canary."""
        if name is None or name not in self.router.names():
            return                 # retired: nothing left to restore
        try:
            _handle_call(self.router.handle_for(name).reload, (),
                         {"step": self.pinned_step,
                          "trace": obs.trace_context()})
        except Exception as e:  # noqa: BLE001 — dead canary
            self.log(f"fleet: could not restore canary {name} to "
                     f"pinned step {self.pinned_step} ({e}); it "
                     f"stays quarantined until it recovers")

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self.state,
                    "pinned_step": self.pinned_step,
                    "target_step": self.target_step,
                    "canary": self.canary,
                    "canaries": self.canaries,
                    "canary_restarts": self.canary_restarts,
                    "promotions": self.promotions,
                    "rollbacks": self.rollbacks,
                    "refusals": self.refusals,
                    "canary_aborts": self.canary_aborts,
                    "torn_polls": self.mgr.torn_polls}

    # -- durable control state (sessionlog.ControlStateStore) ---------------
    def export_state(self) -> Dict[str, Any]:
        """The rollout decisions that must survive a router restart:
        the pinned step (what the fleet serves) and the rejected
        fingerprint (a judged-and-rolled-back checkpoint must not be
        re-canaried by the reborn router)."""
        with self._lock:
            return {"pinned_step": self.pinned_step,
                    "rejected_fp": (list(self._rejected_fp)
                                    if self._rejected_fp is not None
                                    else None)}

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            pinned = state.get("pinned_step")
            if pinned is not None and int(pinned) >= 0:
                self.pinned_step = int(pinned)
            fp = state.get("rejected_fp")
            if fp is not None:
                self._rejected_fp = tuple(fp)


class EngineFleet:
    """N engine workers + router + rollout controller, owned together.
    Build with `EngineFleet.local(...)` (in-process workers) or
    `EngineFleet.adopt(...)` / `EngineFleet.from_hostfile(...)`
    (subprocess workers over HTTP), then `start()`/`stop()` or use as
    a context manager.  `generate`/`predict` route through the fleet
    exactly as `FleetServer`'s HTTP frontend does."""

    def __init__(self, handles: List[Any],
                 workspace: Optional[str] = None,
                 router_spec: Optional[RouterSpec] = None,
                 rollout_spec: Optional[RolloutSpec] = None,
                 tenancy: Optional[TenantRegistry] = None,
                 standby: bool = False, log_fn=print):
        self.log = log_fn
        self.tenancy = tenancy if tenancy is not None \
            else TenantRegistry()
        self.router = Router(handles, spec=router_spec, log_fn=log_fn,
                             tenancy=self.tenancy)
        self.rollout: Optional[RolloutController] = (
            RolloutController(self.router, workspace,
                              spec=rollout_spec, log_fn=log_fn)
            if workspace else None)
        self._local = [h for h in handles
                       if isinstance(h, LocalEngineHandle)]
        # autoscale support: `local()` stashes what it would take to
        # spawn one more identical worker; adopted (HTTP) fleets can't
        # grow from here (spawning remote processes is deployment's
        # job, not the autoscaler's)
        self._spawn_cfg: Optional[Dict[str, Any]] = None
        self._next_idx = len(handles)
        self._grow_lock = threading.Lock()
        # -- crash-safe control plane (sessionlog.py) -------------------
        # a standby holds OFF claiming an epoch: claiming fences the
        # live primary's WAL, which is exactly the handoff and must
        # only happen at promote_standby()
        self.workspace = workspace
        self.standby = bool(standby)
        self.epoch = 0
        self.wal: Optional[SessionWal] = None
        self.wal_stats = WalStats()
        self._state_store: Optional[ControlStateStore] = None
        self.recovered_state: Dict[str, Any] = {}
        # extra durable-state providers (autoscaler etc.): name ->
        # (export_fn, restore_fn); restore happens at recover() time
        # for providers registered before start(), else via
        # `recovered_state`
        self._state_providers: Dict[str, Any] = {}
        self._snap_stop = threading.Event()
        self._snap_thread: Optional[threading.Thread] = None
        if not self.standby:
            self._init_durability()

    # -- crash-safe control plane -------------------------------------------
    def _router_dir(self) -> Optional[str]:
        if not self.workspace:
            return None
        return os.path.join(self.workspace, "router")

    def _init_durability(self) -> None:
        """Claim the next epoch and open this router's WAL.  Claiming
        bumps `<ws>/router/EPOCH`, which self-fences any older router
        still appending to the shared workspace (SessionWal.flush
        re-reads the file) — restart and handoff share one mechanism."""
        dir_ = self._router_dir()
        if dir_ is None or self.router.spec.wal != "on":
            return
        try:
            self.epoch = claim_epoch(dir_)
            self.wal = SessionWal(
                dir_, self.epoch,
                group_tokens=self.router.spec.wal_group_tokens,
                group_ms=self.router.spec.wal_group_ms,
                stats=self.wal_stats, log_fn=self.log)
            self._state_store = ControlStateStore(
                dir_, stats=self.wal_stats)
            self.router.attach_wal(self.wal, self.epoch)
            self.log(f"fleet: session WAL on under epoch "
                     f"{self.epoch} ({dir_})")
        except Exception as e:  # noqa: BLE001 — durability is an
            # add-on: a broken disk degrades to the pre-WAL fleet,
            # counted, never a refusal to serve
            self.wal_stats.count("wal_lost")
            self.log(f"warning: could not open session WAL in "
                     f"{dir_} ({type(e).__name__}: {e}); serving "
                     f"without control-plane durability")
            self.wal = None

    def add_state_provider(self, name: str, export_fn,
                           restore_fn=None) -> None:
        """Register an extra durable-state contributor (e.g. the
        autoscaler's cooldown/streak).  If recovery already ran, the
        provider's slice is in `recovered_state` — restore it now."""
        self._state_providers[name] = (export_fn, restore_fn)
        got = self.recovered_state.get(name)
        if got is not None and restore_fn is not None:
            try:
                restore_fn(got)
            except Exception as e:  # noqa: BLE001
                self.log(f"warning: restoring {name} state failed "
                         f"({e}); starting fresh")

    def export_control_state(self) -> Dict[str, Any]:
        """Everything the next epoch needs that is NOT in the WAL:
        quarantine strikes/benches, shed streaks, rollout pin +
        rejected fingerprint, and any registered provider's slice."""
        state: Dict[str, Any] = {"epoch": self.epoch,
                                 "wall": round(time.time(), 3)}
        state["router"] = self.router.export_control_state()
        if self.rollout is not None:
            state["rollout"] = self.rollout.export_state()
        for name, (export_fn, _r) in self._state_providers.items():
            try:
                state[name] = export_fn()
            except Exception:  # noqa: BLE001 — a provider's failure
                pass           # must not sink the whole snapshot
        return state

    def _snapshot_loop(self) -> None:
        period = float(self.router.spec.state_snapshot_s)
        while not self._snap_stop.wait(period):
            if self._state_store is not None:
                self._state_store.save(self.export_control_state())

    def recover(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Replay the previous epoch's control snapshot and session
        WAL: restore quarantine/rollout/shed-streak state, then
        re-admit every non-terminal journaled stream through the
        durable-session resume path (pinned to the journaled
        fingerprint).  Clients reconnect with X-Session-Id and splice
        exactly-once; a fingerprint-gone stream finishes
        `failover_stale` with the journaled prefix."""
        summary = {"epoch": self.epoch, "state_restored": False,
                   "wal_replayed": None, "torn_tail": False,
                   "sessions": 0, "terminal": 0, "recovered": 0,
                   "failed": 0}
        dir_ = self._router_dir()
        if dir_ is None or self.wal is None:
            return summary
        try:
            faults.maybe_fault("router.recover")
            if self._state_store is not None:
                state = self._state_store.load()
                if state is not None:
                    self.router.restore_control_state(
                        state.get("router") or {})
                    if self.rollout is not None and \
                            state.get("rollout"):
                        self.rollout.restore_state(state["rollout"])
                    self.recovered_state = state
                    for name, (_e, restore_fn) in \
                            self._state_providers.items():
                        if restore_fn is not None and \
                                state.get(name) is not None:
                            restore_fn(state[name])
                    summary["state_restored"] = True
            prev = latest_wal_before(dir_, self.epoch)
            if prev is not None:
                header, records, torn = replay_wal(prev)
                if torn:
                    self.wal_stats.count("torn_tails")
                reduced = reduce_sessions(records)
                for _ in reduced:
                    self.wal_stats.count("replayed_sessions")
                got = self.router.recover_sessions(reduced,
                                                   timeout=timeout)
                for _ in range(int(got.get("recovered", 0))):
                    self.wal_stats.count("recovered_streams")
                summary.update(
                    wal_replayed=os.path.basename(prev),
                    torn_tail=bool(torn), sessions=len(reduced),
                    **{k: int(got.get(k, 0))
                       for k in ("terminal", "recovered", "failed")})
        except Exception as e:  # noqa: BLE001 — a broken replay must
            # never stop the fleet from serving NEW traffic
            self.log(f"warning: control-plane recovery failed "
                     f"({type(e).__name__}: {e}); serving without "
                     f"replayed state")
            summary["error"] = f"{type(e).__name__}: {e}"
        if summary["wal_replayed"] or summary["state_restored"]:
            self.log(f"fleet: recovered control plane under epoch "
                     f"{self.epoch}: {summary['recovered']} stream(s) "
                     f"re-admitted, {summary['terminal']} terminal "
                     f"session(s) retained"
                     + (", torn WAL tail dropped"
                        if summary["torn_tail"] else ""))
        obs.emit_event("router.recover", **{
            k: v for k, v in summary.items() if v is not None})
        return summary

    def handoff(self, successor: Optional[str] = None,
                retry_after: float = 0.5) -> Dict[str, Any]:
        """Lame-duck this router for a zero-downtime handoff: stop
        admitting (409 + successor hint), snapshot control state,
        flush and fence the WAL.  In-flight streams keep running and
        journaled attach/resume stays served; the successor claims
        the next epoch and replays what this router leaves behind."""
        self.router.enter_lame_duck(successor=successor,
                                    retry_after=retry_after)
        if self._state_store is not None:
            self._state_store.save(self.export_control_state())
        if self.wal is not None:
            self.wal.fence()
        self.log(f"fleet: handoff initiated (epoch {self.epoch}"
                 + (f", successor {successor}" if successor else "")
                 + "); WAL fenced, new admissions get 409")
        out = {"epoch": self.epoch, "successor": successor,
               "lame_duck": True}
        obs.emit_event("router.handoff", **out)
        return out

    def promote_standby(self,
                        timeout: Optional[float] = None
                        ) -> Dict[str, Any]:
        """Turn a standby into the primary: claim the next epoch
        (fencing the old primary's WAL), replay its state + WAL, and
        open this fleet for admissions."""
        if not self.standby:
            raise RuntimeError("fleet is not a standby")
        self.standby = False
        self._init_durability()
        got = self.recover(timeout=timeout)
        if self._snap_thread is None and self._state_store is not None:
            self._snap_stop.clear()
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, name="fleet-state-snap",
                daemon=True)
            self._snap_thread.start()
        self.log(f"fleet: standby promoted to primary under epoch "
                 f"{self.epoch}")
        return got

    # -- constructors -------------------------------------------------------
    @classmethod
    def local(cls, net, spec: ServeSpec, size: int,
              workspace: Optional[str] = None, params=None,
              router_spec: Optional[RouterSpec] = None,
              rollout_spec: Optional[RolloutSpec] = None,
              tenancy: Optional[TenantRegistry] = None,
              warmup_modes=("generate",), standby: bool = False,
              log_fn=print) -> "EngineFleet":
        """Spawn `size` in-process engine workers (each its own
        pinned engine, batcher, and stats) over one shared net.  The
        ONE `tenancy` registry is shared by the router and every
        worker's admission path, so quotas agree at every hop."""
        if size < 1:
            raise ValueError(f"fleet size must be >= 1, got {size}")
        tenancy = tenancy if tenancy is not None else TenantRegistry()
        handles = []
        for i in range(size):
            name = f"engine-{i}"
            eng = InferenceEngine(
                net, spec, workspace=workspace, params=params,
                log_fn=(lambda s, n=name: log_fn(f"[{n}] {s}")),
                pinned=True)
            srv = InferenceServer(eng, http=False,
                                  warmup_modes=warmup_modes,
                                  tenancy=tenancy,
                                  log_fn=(lambda s, n=name:
                                          log_fn(f"[{n}] {s}")))
            handles.append(LocalEngineHandle(name, srv))
        fleet = cls(handles, workspace=workspace,
                    router_spec=router_spec,
                    rollout_spec=rollout_spec, tenancy=tenancy,
                    standby=standby, log_fn=log_fn)
        fleet._spawn_cfg = dict(net=net, spec=spec,
                                workspace=workspace, params=params,
                                tenancy=tenancy,
                                warmup_modes=tuple(warmup_modes))
        fleet._next_idx = size
        return fleet

    @classmethod
    def adopt(cls, urls: List[str], workspace: Optional[str] = None,
              router_spec: Optional[RouterSpec] = None,
              rollout_spec: Optional[RolloutSpec] = None,
              tenancy: Optional[TenantRegistry] = None,
              standby: bool = False, log_fn=print,
              transport: str = "auto") -> "EngineFleet":
        """Adopt already-running engine processes by base URL.

        `transport` picks the per-engine data plane: "auto" (default)
        negotiates per engine — the HTTP /healthz probe discovers a
        `wire_port` and upgrades that engine's requests/streams to
        the binary framed transport, degrading back to HTTP on any
        wire failure (serve/wire.py); "http" pins the debug surface
        unconditionally.  Mixed fleets are first-class: each engine
        negotiates independently, so routing, hedging, and failover
        cross the binary/HTTP boundary freely."""
        if transport not in ("auto", "http"):
            raise ValueError(f"transport must be auto|http, got "
                             f"{transport!r}")
        if transport == "auto":
            handles = [NegotiatingEngineHandle(f"engine-{i}", u,
                                               log_fn=log_fn)
                       for i, u in enumerate(urls)]
        else:
            handles = [HttpEngineHandle(f"engine-{i}", u)
                       for i, u in enumerate(urls)]
        return cls(handles, workspace=workspace,
                   router_spec=router_spec, rollout_spec=rollout_spec,
                   tenancy=tenancy, standby=standby, log_fn=log_fn)

    @classmethod
    def from_hostfile(cls, path: str, default_port: int = 8000,
                      **kw) -> "EngineFleet":
        """Adopt membership from a hostfile (one engine `host[:port]`
        per line — `parallel.bootstrap.parse_hostfile`, which rejects
        duplicates and empty membership)."""
        from ..parallel.bootstrap import parse_hostfile
        hosts = parse_hostfile(path)
        urls = [f"http://{h}" if ":" in h
                else f"http://{h}:{default_port}" for h in hosts]
        return cls.adopt(urls, **kw)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "EngineFleet":
        for h in self._local:
            h.start()
        self.router.start()
        # restore + replay BEFORE the rollout controller pins: a
        # restored pinned step must win over the members' cold-start
        # step, and recovered streams need engines adopted first
        if not self.standby:
            self.recover()
        if self.rollout is not None:
            # pin the fleet at the step the members actually serve —
            # unless recovery restored a promoted pin (restore_state
            # already set it; keep the max so a newer promotion that
            # members still serve is not walked back)
            steps = [self.router.engine_step(n)
                     for n in self.router.names()]
            pin = max(steps) if steps else -1
            self.rollout.start(max(pin, self.rollout.pinned_step))
        if not self.standby and self._state_store is not None and \
                self._snap_thread is None:
            self._snap_stop.clear()
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, name="fleet-state-snap",
                daemon=True)
            self._snap_thread.start()
        n_ok = len(self.router.healthy_names())
        self.log(f"fleet: {n_ok}/{len(self.router.names())} engine(s) "
                 f"healthy"
                 + (f", rollout pinned at step "
                    f"{self.rollout.pinned_step}"
                    if self.rollout is not None else "")
                 + (" [STANDBY: admissions closed until promote]"
                    if self.standby else ""))
        return self

    def stop(self) -> None:
        self._snap_stop.set()
        if self._snap_thread is not None:
            self._snap_thread.join(5.0)
            self._snap_thread = None
        if self.rollout is not None:
            self.rollout.stop()
        self.router.stop()
        if self.wal is not None:
            self.wal.close()
        for h in self._local:
            if h._alive:
                h.stop()
        # remote handles: drop pooled keep-alive sockets and any
        # persistent binary connections
        for name in self.router.names():
            h = self.router.handle_for(name)
            if h not in self._local and hasattr(h, "close"):
                try:
                    h.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    def __enter__(self) -> "EngineFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- elastic membership (autoscaler surface) ----------------------------
    def can_grow(self) -> bool:
        return self._spawn_cfg is not None

    def grow(self) -> str:
        """Spawn, warm, and pin ONE new in-process worker, then hand
        it to the Router.  The ordering is the contract: load +
        warmup compiles + reload-to-pinned-step all happen BEFORE
        `add_engine` — a cold engine must never eat live traffic.
        Returns the new engine's name."""
        cfg = self._spawn_cfg
        if cfg is None:
            raise RuntimeError("fleet cannot grow: not built with "
                               "EngineFleet.local()")
        with self._grow_lock:
            name = f"engine-{self._next_idx}"
            self._next_idx += 1
        eng = InferenceEngine(
            cfg["net"], cfg["spec"], workspace=cfg["workspace"],
            params=cfg["params"],
            log_fn=(lambda s, n=name: self.log(f"[{n}] {s}")),
            pinned=True)
        srv = InferenceServer(eng, http=False,
                              warmup_modes=cfg["warmup_modes"],
                              tenancy=cfg.get("tenancy"),
                              log_fn=(lambda s, n=name:
                                      self.log(f"[{n}] {s}")))
        h = LocalEngineHandle(name, srv)
        h.start()                  # load + warmup compiles happen here
        pinned = (self.rollout.pinned_step
                  if self.rollout is not None else None)
        if pinned is not None and pinned >= 0 and \
                eng.params_step != pinned:
            got = h.reload(step=pinned)
            if int(got.get("step", -1)) != pinned:
                h.stop()
                raise RuntimeError(
                    f"new engine {name} could not reach pinned step "
                    f"{pinned} (landed {got.get('step')}); not joined")
        self._local.append(h)
        self.router.add_engine(h)
        return name

    def retire(self, name: str, drain: bool = True,
               timeout_s: float = 30.0) -> bool:
        """Drain and retire one worker through the Router's
        membership path; stop its server once drained.  On a drain
        timeout the handle is left running (still in `_local`) so
        in-flight streams can finish — `stop()` cleans it up."""
        drained = self.router.remove_engine(name, drain=drain,
                                            timeout_s=timeout_s)
        h = next((x for x in self._local if x.name == name), None)
        if h is not None and (drained or not drain):
            self._local.remove(h)
            if h._alive:
                h.stop()
        return drained

    # -- client API ---------------------------------------------------------
    def generate(self, tokens, timeout=None, deadline=None,
                 priority="interactive", tenant=None,
                 model=None) -> Dict[str, Any]:
        return self.router.route("generate", tokens, timeout=timeout,
                                 deadline=deadline, priority=priority,
                                 tenant=tenant, model=model)

    def generate_stream(self, tokens, timeout=None, max_new=None,
                        deadline=None, priority="interactive",
                        tenant=None, model=None):
        """Streaming generate through the fleet (cb members only):
        yields {"token": t} events then the {"done": True, ...}
        summary; retries on another engine only before the first
        event (Router.route_stream)."""
        return self.router.route_stream(tokens, timeout=timeout,
                                        max_new=max_new,
                                        deadline=deadline,
                                        priority=priority,
                                        tenant=tenant, model=model)

    def predict(self, tokens, timeout=None, deadline=None,
                priority="interactive", tenant=None,
                model=None) -> Dict[str, Any]:
        return self.router.route("predict", tokens, timeout=timeout,
                                 deadline=deadline, priority=priority,
                                 tenant=tenant, model=model)

    def snapshot(self) -> Dict[str, Any]:
        out = self.router.snapshot()
        if self.rollout is not None:
            out["rollout"] = self.rollout.snapshot()
        out["standby"] = self.standby
        if self.wal is not None or self.standby:
            out["wal"] = self.wal_stats.snapshot()
        return out


# -- HTTP frontend ----------------------------------------------------------

class FleetServer:
    """The fleet's own stdlib-HTTP frontend (the single-engine
    `InferenceServer`'s shape, one level up): POST /generate and
    /predict route through the fleet; GET /stats, /metrics, /healthz
    read the router.  /healthz is honest at fleet level too: 200 while
    at least one engine is healthy, 503 when the whole fleet is."""

    def __init__(self, fleet: EngineFleet, host: str = "127.0.0.1",
                 port: int = 0, log_fn=print):
        from ..obs.metrics import MetricsRegistry
        from ..obs import perf
        self.fleet = fleet
        self.log = log_fn
        self.metrics = MetricsRegistry()
        self.fleet.router.stats.register_into(self.metrics)
        # performance observatory + process-level collector: the fleet
        # frontend exports the same compile/HBM/RSS surface as every
        # other /metrics endpoint
        perf.register_into(self.metrics)
        perf.register_process_into(self.metrics)
        # durable-stream session counters (singa_stream_*): failover /
        # splice / dedupe visibility next to the fleet counters
        self.fleet.router.sessions.stats.register_into(self.metrics)
        # control-plane durability (singa_router_wal_*): appends,
        # bytes, lost writes, fenced writes, replay/recovery counts
        self.fleet.wal_stats.register_into(self.metrics)
        # binary-transport counters + serialization-time split
        # (singa_wire_*): frames, malformed, fallbacks, ser/deser vs
        # json_ser/json_deser seconds — the transport A/B evidence
        wire.register_into(self.metrics)
        self._host, self._port = host, port
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None

    def start(self) -> "FleetServer":
        import json
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        import numpy as np

        from . import qos as _qos
        from .batcher import DeadlineExpired as _DE
        from .batcher import Overloaded as _OL
        from .router import UnknownModel as _UM

        fleet, metrics = self.fleet, self.metrics

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if fleet.epoch:
                    self.send_header(_qos.EPOCH_HEADER,
                                     str(fleet.epoch))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/stats":
                    self._reply(200, fleet.snapshot())
                elif self.path == "/trace":
                    # this process's span ring, Perfetto-shaped —
                    # obs.collect merges it with the workers' rings
                    self._reply(200, obs.trace_dump())
                elif self.path == "/debug/requests":
                    # per-request lifecycle records: last-N + slowest-N
                    # with stage attribution (router.RequestLog)
                    self._reply(200, fleet.router.requests.snapshot())
                elif self.path == "/metrics":
                    body = metrics.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/control/state":
                    # the durable control snapshot, live — what a
                    # successor (or an operator) would recover from
                    self._reply(200, fleet.export_control_state())
                elif self.path == "/healthz":
                    healthy = len(fleet.router.healthy_names())
                    total = len(fleet.router.names())
                    if fleet.standby:
                        # a standby is HEALTHY-but-not-serving: load
                        # balancers must not route to it, operators
                        # must see it alive and promotable
                        self._reply(200, {
                            "ok": True, "status": "standby",
                            "healthy_engines": healthy,
                            "engines": total})
                        return
                    ok = healthy > 0
                    status = "ok" if ok else "degraded"
                    if ok and fleet.router.lame_duck is not None:
                        status = "lame_duck"
                    self._reply(200 if ok else 503, {
                        "ok": ok,
                        "status": status,
                        "healthy_engines": healthy,
                        "engines": total})
                else:
                    self._reply(404,
                                {"error": f"no route {self.path}"})

            def _chunk(self, data):
                self.wfile.write(f"{len(data):X}\r\n".encode()
                                 + data + b"\r\n")

            def _remote_trace(self):
                """Client-supplied trace context (X-Trace-Id /
                X-Parent-Span), or None — malformed headers degrade
                to a fresh trace, never a 400 (qos.py)."""
                return _qos.trace_from_headers(
                    self.headers.get(_qos.TRACE_HEADER),
                    self.headers.get(_qos.PARENT_SPAN_HEADER))

            def _stream(self, req):
                """Chunked passthrough: re-serialize the engine's
                token events as they arrive — the full body is never
                buffered at the fleet tier.  route_stream raises
                BEFORE the 200 when no engine admits the stream, so
                admission errors keep their status codes; a
                mid-stream failure becomes a terminal {"error": ...}
                line.  A `session`/X-Session-Id reconnect ATTACHES to
                the journaled stream instead of admitting a new one —
                the restart/handoff resume path, deliberately served
                even while lame-ducked."""
                sid = req.get("session") or \
                    self.headers.get(_qos.SESSION_HEADER)
                if sid:
                    stream = fleet.router.attach_stream(
                        str(sid),
                        resume_from=int(req.get("resume_from", 0)))
                else:
                    tokens = np.asarray(req["tokens"], np.int32)
                    mn = req.get("max_new")
                    link = self._remote_trace()
                    # degrade-never-reject: garbled tenant folds to
                    # "default" (qos.check_tenant cannot raise)
                    tenant = _qos.check_tenant(
                        req.get("tenant")
                        or self.headers.get(_qos.TENANT_HEADER))
                    # the span covers ADMISSION only (route_stream
                    # admits eagerly and returns the generator) — the
                    # router's stream spans anchor to it via the
                    # thread-local; a span must never stay open across
                    # generator yields
                    with obs.span("fleet.request", mode="stream",
                                  tenant=tenant,
                                  trace=link[0] if link else None,
                                  parent=((link[1] or None)
                                          if link else None)):
                        stream = fleet.router.route_stream(
                            tokens, timeout=req.get("timeout"),
                            max_new=None if mn is None else int(mn),
                            deadline=_qos.deadline_from_header(
                                self.headers.get(
                                    _qos.DEADLINE_HEADER)),
                            priority=_qos.check_priority(
                                req.get("priority")
                                or self.headers.get(
                                    _qos.PRIORITY_HEADER)),
                            tenant=tenant, model=req.get("model"))
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                if fleet.epoch:
                    self.send_header(_qos.EPOCH_HEADER,
                                     str(fleet.epoch))
                self.end_headers()
                # batched token flushes (serve/wire.py): several
                # ndjson lines per chunked write under the router
                # spec's flush knobs.  The coalescer flushes the
                # first line of the stream immediately — first-token
                # latency is a gated stage
                co = wire.LineCoalescer(
                    self._chunk,
                    flush_tokens=fleet.router.spec.flush_tokens,
                    flush_ms=fleet.router.spec.flush_ms)
                try:
                    for ev in stream:
                        co.add(wire.timed_json_dumps(ev) + b"\n",
                               urgent=bool(ev.get("done")))
                except Exception as e:  # noqa: BLE001 — mid-stream
                    co.add(json.dumps(
                        {"error":
                         f"{type(e).__name__}: {e}"}).encode()
                        + b"\n", urgent=True)
                co.flush()
                self._chunk(b"")

            def do_POST(self):
                if self.path == "/admin/handoff":
                    self._admin_handoff()
                    return
                if self.path == "/admin/promote":
                    self._admin_promote()
                    return
                mode = self.path.lstrip("/")
                if mode not in ("generate", "predict"):
                    self._reply(404,
                                {"error": f"no route {self.path}"})
                    return
                if fleet.standby:
                    # the standby's data plane is closed until it is
                    # promoted: routing here would split-brain the
                    # session journal across two unfenced writers
                    self._reply(503, {
                        "error": "standby router: promote before "
                                 "sending traffic",
                        "status": "standby"},
                        {"Retry-After": "1.0"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if mode == "generate" and req.get("stream"):
                        self._stream(req)
                        return
                    tokens = np.asarray(req["tokens"], np.int32)
                    link = self._remote_trace()
                    tenant = _qos.check_tenant(
                        req.get("tenant")
                        or self.headers.get(_qos.TENANT_HEADER))
                    with obs.span("fleet.request", mode=mode,
                                  tenant=tenant,
                                  trace=link[0] if link else None,
                                  parent=((link[1] or None)
                                          if link else None)):
                        out = fleet.router.route(
                            mode, tokens,
                            timeout=req.get("timeout"),
                            deadline=_qos.deadline_from_header(
                                self.headers.get(
                                    _qos.DEADLINE_HEADER)),
                            priority=_qos.check_priority(
                                req.get("priority")
                                or self.headers.get(
                                    _qos.PRIORITY_HEADER)),
                            tenant=tenant, model=req.get("model"))
                    self._reply(200, out)
                except _UM as e:
                    # honest fast 404: the fleet does not serve this
                    # model family — never a shed, never a strike
                    self._reply(404, {"error": str(e)})
                except LameDuck as e:
                    # handing off: 409 points the client at the
                    # successor — before KeyError/RuntimeError arms
                    # (LameDuck IS a RuntimeError)
                    self._reply(409, {"error": str(e),
                                      "successor": e.successor,
                                      "retry_after": e.retry_after},
                                {"Retry-After":
                                 f"{e.retry_after:.3f}"})
                except UnknownSession as e:
                    # 410 Gone, not 404: the sid grammar was right but
                    # the journaled session is finished-and-evicted or
                    # never existed — retrying cannot help
                    self._reply(410, {"error": str(e)})
                except _OL as e:
                    self._reply(503, {"error": str(e),
                                      "retry_after": e.retry_after},
                                {"Retry-After":
                                 f"{e.retry_after:.3f}"})
                except (_DE, TimeoutError) as e:
                    self._reply(504, {"error": str(e)})
                except (KeyError, ValueError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error":
                                      f"{type(e).__name__}: {e}"})

            def _admin_handoff(self):
                """Lame-duck this router for a zero-downtime handoff
                (EngineFleet.handoff): body {"successor": url?,
                "retry_after": s?}."""
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    out = fleet.handoff(
                        successor=req.get("successor"),
                        retry_after=float(req.get("retry_after",
                                                  0.5)))
                    self._reply(200, out)
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error":
                                      f"{type(e).__name__}: {e}"})

            def _admin_promote(self):
                """Promote a standby to primary: claim the next
                epoch (fencing the old primary) and replay its WAL."""
                try:
                    got = fleet.promote_standby()
                    self._reply(200, got)
                except RuntimeError as e:
                    # not a standby: promoting a live primary would
                    # fence ITS OWN WAL out from under it
                    self._reply(409, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error":
                                      f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-http",
            daemon=True)
        self._http_thread.start()
        self.log(f"fleet: http on {self.address[0]}:"
                 f"{self.address[1]}")
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._http_thread = None

    @property
    def address(self):
        return self._httpd.server_address if self._httpd else None
