"""SLO-driven fleet autoscaler: fit capacity to the workload.

A fixed `--fleet N` is wrong twice a day: under a flash crowd the
Router sheds (capacity too small), and at night N−1 engines idle
(capacity too large).  The `AutoScaler` closes that loop with the
signals the serving tier already publishes — no new instrumentation,
just a control law over the windowed views:

    shed_rate    RouterStats.windowed() — requests shed / routed over
                 the last `window_s`; the most direct overload signal
    p95          RouterStats.windowed() p95 vs the `slo_p95_ms` budget
    queue depth  probed per-member depth summed over active members
    occupancy    per-engine ServeStats cb_slot_occupancy_recent (or
                 batch occupancy) — saturation BEFORE shedding starts
    lag          pipeline blessed→served lag (when running under
                 `PipelineController`) — a fleet too busy to promote
                 is not a fleet to shrink

Control law (one `tick()` every `tick_s`):

    UP    any pressure signal over its bound → `EngineFleet.grow()`:
          spawn + load + warmup-compile + reload-to-pinned-step all
          happen BEFORE the Router sees the new member — a cold
          engine must never eat live traffic.
    DOWN  only after `quiet_ticks` CONSECUTIVE quiet ticks (no sheds,
          p95 under `down_margin` × SLO, low occupancy, zero lag) —
          the hysteresis that stops flapping — and the victim drains
          through the Router's membership path: admissions stop
          immediately, in-flight work (held stream slots included)
          finishes, then the member retires.  The rollout canary is
          never picked as the victim.
    HOLD  pressure at `max_engines`, or quiet at `min_engines`, or
          inside the `Backoff`-escalated cooldown after any action.

`scale.decide` fault site: a faulted tick skips the decision entirely
(counted `decide_faults`, evented `scale.abort`) — fault injection can
never retire an engine.  Telemetry: `singa_autoscale_*` counters and
gauges via `register_into`, `scale.up` / `scale.down` / `scale.hold` /
`scale.abort` events, `scale.tick` spans (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .. import obs
from ..utils import faults


@dataclass(frozen=True)
class AutoScaleSpec:
    """`--autoscale_spec` grammar (the ServeSpec mold):
    comma/semicolon-separated `key=value`."""
    slo_p95_ms: float = 200.0     # the latency budget
    max_shed_rate: float = 0.02   # tolerated windowed shed fraction
    w_batch: float = 0.5          # batch-shed weight in the signal
    w_best_effort: float = 0.0    # best_effort-shed weight (default:
                                  # shedding best_effort is the plan,
                                  # not a reason to buy capacity)
    min_engines: int = 1
    max_engines: int = 4
    cooldown_s: float = 5.0       # Backoff base between actions
    window_s: float = 10.0        # signal sliding window
    tick_s: float = 0.25          # control-loop cadence
    down_margin: float = 0.5      # quiet iff p95 < margin * SLO
    queue_high: float = 4.0       # pressure iff depth > n * queue_high
    occ_high: float = 0.9         # pressure iff occupancy above this
    quiet_ticks: int = 3          # consecutive quiet ticks before DOWN
    drain_timeout_s: float = 30.0
    seed: int = 0

    def __post_init__(self):
        if int(self.min_engines) < 1:
            raise ValueError(f"min_engines must be >= 1, got "
                             f"{self.min_engines}")
        if int(self.max_engines) < int(self.min_engines):
            raise ValueError(
                f"max_engines ({self.max_engines}) must be >= "
                f"min_engines ({self.min_engines})")
        if float(self.slo_p95_ms) <= 0:
            raise ValueError(f"slo_p95_ms must be > 0, got "
                             f"{self.slo_p95_ms}")
        if float(self.window_s) <= 0 or float(self.tick_s) <= 0:
            raise ValueError("window_s and tick_s must be > 0")
        if float(self.cooldown_s) < 0:
            raise ValueError(f"cooldown_s must be >= 0, got "
                             f"{self.cooldown_s}")
        if not 0 < float(self.down_margin) < 1:
            raise ValueError(f"down_margin must be in (0, 1), got "
                             f"{self.down_margin}")
        if int(self.quiet_ticks) < 1:
            raise ValueError(f"quiet_ticks must be >= 1, got "
                             f"{self.quiet_ticks}")
        for name in ("w_batch", "w_best_effort"):
            if not 0 <= float(getattr(self, name)) <= 1:
                raise ValueError(f"{name} must be in [0, 1], got "
                                 f"{getattr(self, name)}")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "AutoScaleSpec":
        kw: Dict[str, Any] = {}
        types = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key, sep, val = part.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or key not in types:
                    raise ValueError(f"unknown key {key!r}")
                kw[key] = (float(val) if "float" in str(types[key])
                           else int(val))
            except ValueError as e:
                raise ValueError(f"bad autoscale spec entry {part!r} "
                                 f"(want key=value): {e}") from e
        return cls(**kw)


class AutoScaler:
    """See module docstring.  One daemon thread ticks every
    `spec.tick_s`; `tick()` is also callable directly (tests and the
    bench drive control timing deterministically).  Scale-up runs
    inline (the compile cost IS the action); scale-down drains on a
    background thread so a slow drain never freezes the control
    loop."""

    def __init__(self, fleet, spec: Optional[AutoScaleSpec] = None,
                 lag_fn=None, log_fn=print):
        self.fleet = fleet
        self.spec = spec or AutoScaleSpec()
        self.lag_fn = lag_fn         # () -> {"lag_steps": ...} or None
        self.log = log_fn
        self._backoff = faults.Backoff(base=max(self.spec.cooldown_s,
                                                1e-3),
                                       cap=max(self.spec.cooldown_s,
                                               1e-3) * 8,
                                       seed=self.spec.seed)
        self._cooldown_until = 0.0
        self._streak = 0             # same-direction actions in a row
        self._last_dir: Optional[str] = None
        self._quiet = 0              # consecutive quiet ticks
        self._busy = False           # one membership action at a time
        self._action_thread: Optional[threading.Thread] = None
        # outcome counters (snapshot / singa_autoscale_*)
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.holds = 0
        self.aborts = 0
        self.decide_faults = 0
        self.grow_failures = 0
        self.drained_clean = 0
        self.drain_timeouts = 0
        self.last_decision: str = "none"
        self.last_why: str = ""
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "AutoScaler":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10.0)
            self._thread = None
        t = self._action_thread
        if t is not None:
            t.join(self.spec.drain_timeout_s + 5.0)
            self._action_thread = None

    def _loop(self) -> None:
        while not self._stop.wait(float(self.spec.tick_s)):
            self.tick()

    # -- signals ------------------------------------------------------------
    def signals(self) -> Dict[str, Any]:
        """One coherent reading of every control input.  `n` counts
        ACTIVE members only — a draining engine is capacity already
        spent, not capacity to reason about.

        The shed signal is CLASS-WEIGHTED: an interactive shed counts
        1.0, a batch shed `w_batch`, a best_effort shed
        `w_best_effort` (default 0 — brownout shedding best_effort is
        the system working, not a reason to buy capacity).  The raw
        all-classes rate stays visible as `shed_rate_raw`.  p95 is the
        INTERACTIVE class p95 when that class has completions — the
        SLO is theirs; batch latency must not trigger scale-ups."""
        win = self.fleet.router.stats.windowed(self.spec.window_s)
        members = [m for m in self.fleet.router.members()
                   if not m.get("draining")]
        occ = None
        for m in members:
            if not m["healthy"] or m["quarantined"]:
                continue
            try:
                snap = self.fleet.router.handle_for(
                    m["name"]).stats_snapshot()
            except Exception:  # noqa: BLE001 — retired/dead mid-read
                continue
            v = snap.get("cb_slot_occupancy_recent")
            if v is None:
                v = snap.get("batch_occupancy")
            if v is not None:
                occ = v if occ is None else max(occ, v)
        lag_steps = 0
        if self.lag_fn is not None:
            try:
                lag_steps = int((self.lag_fn() or {}).get(
                    "lag_steps") or 0)
            except Exception:  # noqa: BLE001 — pipeline winding down
                lag_steps = 0
        by_class = win.get("shed_by_class") or {}
        weighted = (by_class.get("interactive", 0) * 1.0
                    + by_class.get("batch", 0)
                    * float(self.spec.w_batch)
                    + by_class.get("best_effort", 0)
                    * float(self.spec.w_best_effort))
        # TENANT-WEIGHTED on top of class-weighted: a shed charged to
        # a quota-limited tenant counts only `share` (its queue_frac)
        # of a shed from an unconstrained one — a tenant overflowing
        # its OWN entitlement is blast-radius containment working,
        # not a reason to buy fleet-wide capacity.  The discount is
        # the share-weighted mean over the window's sheds; with no
        # tenancy configured every share is 1.0 and the factor is 1.0
        # (legacy control law unchanged).
        tenant_factor = 1.0
        by_tenant = win.get("shed_by_tenant") or {}
        total_t = sum(by_tenant.values())
        reg = getattr(getattr(self.fleet, "router", None),
                      "tenancy", None)
        if total_t > 0 and reg is not None:
            tw = sum(cnt * float(reg.share(t))
                     for t, cnt in by_tenant.items())
            tenant_factor = tw / total_t
        weighted *= tenant_factor
        p95_cls = (win.get("p95_by_class") or {}).get("interactive")
        return {
            "n": len(members),
            "healthy": sum(1 for m in members
                           if m["healthy"] and not m["quarantined"]),
            "queue_depth": sum(m["queue_depth"] + m["in_flight"]
                               for m in members),
            "shed_rate": round(weighted / max(win["routed"], 1), 4),
            "shed_rate_raw": win["shed_rate"],
            "tenant_shed_factor": round(tenant_factor, 4),
            "qps": win["qps"],
            "p95_ms": (p95_cls if p95_cls is not None
                       else win["p95_latency_ms"]),
            "occupancy": occ,
            "lag_steps": lag_steps,
        }

    # -- control law --------------------------------------------------------
    def decide(self, sig: Dict[str, Any]) -> Dict[str, Any]:
        """Decision from one signal reading: {"dir": "up" | "down" |
        "hold", "why": ...}.  Touches nothing but the quiet-streak
        counter, so the control law is unit-testable on fabricated
        signals."""
        s = self.spec
        n = sig["n"]
        pressure: List[str] = []
        if sig["shed_rate"] > float(s.max_shed_rate):
            pressure.append(f"shed_rate {sig['shed_rate']:.3f} > "
                            f"{s.max_shed_rate}")
        if sig["p95_ms"] is not None and \
                sig["p95_ms"] > float(s.slo_p95_ms):
            pressure.append(f"p95 {sig['p95_ms']:.1f}ms > SLO "
                            f"{s.slo_p95_ms}ms")
        if sig["queue_depth"] > n * float(s.queue_high):
            pressure.append(f"queue depth {sig['queue_depth']} > "
                            f"{n} x {s.queue_high}")
        if sig["occupancy"] is not None and \
                sig["occupancy"] > float(s.occ_high):
            pressure.append(f"occupancy {sig['occupancy']:.2f} > "
                            f"{s.occ_high}")
        if pressure:
            self._quiet = 0
            if n >= int(s.max_engines):
                return {"dir": "hold",
                        "why": f"pressure at max_engines "
                               f"({'; '.join(pressure)})"}
            return {"dir": "up", "why": "; ".join(pressure)}
        quiet = (sig["shed_rate"] == 0
                 and (sig["p95_ms"] is None
                      or sig["p95_ms"] < float(s.slo_p95_ms)
                      * float(s.down_margin))
                 and (sig["occupancy"] is None
                      or sig["occupancy"] < float(s.occ_high) / 2)
                 and sig["lag_steps"] == 0)
        if not quiet:
            self._quiet = 0
            return {"dir": "hold", "why": "inside the SLO band"}
        self._quiet += 1
        if n <= int(s.min_engines):
            self._quiet = min(self._quiet, int(s.quiet_ticks))
            return {"dir": "hold", "why": "quiet at min_engines"}
        if self._quiet < int(s.quiet_ticks):
            return {"dir": "hold",
                    "why": f"quiet streak {self._quiet}/"
                           f"{s.quiet_ticks}"}
        return {"dir": "down",
                "why": f"{self._quiet} consecutive quiet ticks"}

    # -- one tick -----------------------------------------------------------
    def tick(self) -> Optional[str]:
        """One control step; returns the action taken ("up", "down",
        "hold", "abort", or None while a previous action is still in
        flight).  A faulted or crashed tick skips the decision — it
        never spawns and NEVER retires an engine."""
        with self._lock:
            self.ticks += 1
            if self._busy:
                return None          # one membership action at a time
        try:
            with obs.span("scale.tick"):
                faults.maybe_fault("scale.decide")
                sig = self.signals()
                verdict = self.decide(sig)
        except Exception as e:  # noqa: BLE001 — skip, never kill
            with self._lock:
                self.decide_faults += 1
                self.aborts += 1
                self.last_decision, self.last_why = \
                    "abort", f"{type(e).__name__}: {e}"
            self.log(f"autoscale: tick aborted "
                     f"({type(e).__name__}: {e}); no decision taken")
            obs.emit_event("scale.abort",
                           why=f"{type(e).__name__}: {e}")
            return "abort"
        now = time.monotonic()
        if verdict["dir"] != "hold" and now < self._cooldown_until:
            with self._lock:
                self.holds += 1
                self.last_decision = "hold"
                self.last_why = (f"cooldown "
                                 f"({self._cooldown_until - now:.1f}s "
                                 f"left); wanted {verdict['dir']}: "
                                 f"{verdict['why']}")
            obs.emit_event("scale.hold", why=self.last_why,
                           wanted=verdict["dir"], n=sig["n"])
            return "hold"
        if verdict["dir"] == "hold":
            with self._lock:
                self.holds += 1
                self.last_decision, self.last_why = \
                    "hold", verdict["why"]
            return "hold"
        self._arm_cooldown(verdict["dir"])
        if verdict["dir"] == "up":
            return self._scale_up(sig, verdict["why"])
        return self._scale_down(sig, verdict["why"])

    def _arm_cooldown(self, direction: str) -> None:
        streak = (self._streak + 1 if direction == self._last_dir
                  else 0)
        self._streak, self._last_dir = streak, direction
        self._cooldown_until = time.monotonic() + \
            self._backoff.delay(streak)

    def _scale_up(self, sig: Dict[str, Any], why: str) -> str:
        with obs.span("scale.up", n=sig["n"]):
            try:
                name = self.fleet.grow()
            except Exception as e:  # noqa: BLE001 — keep serving at n
                with self._lock:
                    self.grow_failures += 1
                    self.aborts += 1
                    self.last_decision = "abort"
                    self.last_why = f"grow failed: {e}"
                self.log(f"autoscale: scale-up FAILED ({e}); fleet "
                         f"stays at {sig['n']}")
                obs.emit_event("scale.abort", why=f"grow failed: {e}",
                               n=sig["n"])
                return "abort"
        with self._lock:
            self.scale_ups += 1
            self.last_decision, self.last_why = "up", why
        self._quiet = 0
        self.log(f"autoscale: scaled UP to {sig['n'] + 1} "
                 f"(joined {name}): {why}")
        obs.emit_event("scale.up", engine=name, n=sig["n"] + 1,
                       why=why)
        return "up"

    def _pick_victim(self) -> Optional[str]:
        """Least valuable active member: quarantined engines first,
        then the least-loaded — and never the rollout canary (retiring
        it would abort a rollout just to save one engine)."""
        canary = (self.fleet.rollout.canary
                  if self.fleet.rollout is not None else None)
        cands = [m for m in self.fleet.router.members()
                 if not m.get("draining") and m["name"] != canary]
        if not cands:
            return None
        cands.sort(key=lambda m: (
            m["healthy"] and not m["quarantined"],   # sick first
            m["in_flight"] + m["queue_depth"]))      # then idle first
        return cands[0]["name"]

    def _scale_down(self, sig: Dict[str, Any], why: str) -> str:
        victim = self._pick_victim()
        if victim is None:
            with self._lock:
                self.holds += 1
                self.last_decision = "hold"
                self.last_why = "no retirable engine"
            obs.emit_event("scale.hold", why="no retirable engine",
                           n=sig["n"])
            return "hold"
        with self._lock:
            self._busy = True
        self._quiet = 0

        def drain():
            try:
                with obs.span("scale.down", engine=victim,
                              n=sig["n"]):
                    drained = self.fleet.retire(
                        victim, drain=True,
                        timeout_s=self.spec.drain_timeout_s)
                with self._lock:
                    self.scale_downs += 1
                    if drained:
                        self.drained_clean += 1
                    else:
                        self.drain_timeouts += 1
                    self.last_decision, self.last_why = "down", why
                self.log(f"autoscale: scaled DOWN to {sig['n'] - 1} "
                         f"(retired {victim}, "
                         f"{'drained' if drained else 'drain timed out'}"
                         f"): {why}")
                obs.emit_event("scale.down", engine=victim,
                               n=sig["n"] - 1, drained=drained,
                               why=why)
            finally:
                with self._lock:
                    self._busy = False

        t = threading.Thread(target=drain, name="fleet-scale-down",
                             daemon=True)
        self._action_thread = t
        t.start()
        return "down"

    # -- durable control state (fleet state provider) ------------------------
    def export_state(self) -> Dict[str, Any]:
        """What a reborn autoscaler must remember: the cooldown still
        in force (as remaining seconds — monotonic clocks don't
        survive a restart) and the same-direction streak that sized
        it.  Without this a crash-restart forgets the cooldown and
        can oscillate immediately — the exact flap damping exists to
        prevent."""
        with self._lock:
            rem = max(self._cooldown_until - time.monotonic(), 0.0)
            return {"cooldown_remaining_s": round(rem, 3),
                    "streak": self._streak,
                    "last_dir": self._last_dir}

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            rem = float(state.get("cooldown_remaining_s", 0.0))
            if rem > 0:
                self._cooldown_until = time.monotonic() + rem
            self._streak = max(int(state.get("streak", 0)), 0)
            last = state.get("last_dir")
            self._last_dir = str(last) if last is not None else None

    # -- reads --------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {"ticks": self.ticks,
                   "scale_ups": self.scale_ups,
                   "scale_downs": self.scale_downs,
                   "holds": self.holds,
                   "aborts": self.aborts,
                   "decide_faults": self.decide_faults,
                   "grow_failures": self.grow_failures,
                   "drained_clean": self.drained_clean,
                   "drain_timeouts": self.drain_timeouts,
                   "last_decision": self.last_decision,
                   "last_why": self.last_why,
                   "busy": self._busy}
        out["engines"] = len([m for m in self.fleet.router.members()
                              if not m.get("draining")])
        out["quiet_streak"] = self._quiet
        return out

    def register_into(self, registry,
                      prefix: str = "singa_autoscale") -> None:
        from ..obs.metrics import Sample

        counters = ("ticks", "scale_ups", "scale_downs", "holds",
                    "aborts", "decide_faults", "grow_failures",
                    "drained_clean", "drain_timeouts")

        def collect():
            snap = self.snapshot()
            out = [Sample(f"{prefix}_{k}_total", "counter",
                          f"autoscaler counter {k!r}", float(snap[k]))
                   for k in counters]
            out += [Sample(f"{prefix}_{k}", "gauge",
                           f"autoscaler gauge {k!r}", float(snap[k]))
                    for k in ("engines", "quiet_streak")]
            return out

        registry.register_collector(collect)
