"""Serving-tier counters — the inference-side sibling of
`data.pipeline.PipelineStats`.

One `ServeStats` instance is shared by the `InferenceEngine` (compile /
reload accounting), the `MicroBatcher` (admission / batching / latency),
and the `InferenceServer` (the /stats endpoint).  All mutation goes
through the lock; `snapshot()` is the single read surface, so the HTTP
handler, the bench smoke, and tests all see the same semantics:

  * latency quantiles (p50/p95) come from a bounded reservoir of the
    most recent completions — a serving dashboard number, not an exact
    all-time percentile;
  * `occupancy` is real requests / bucket batch slots averaged over
    dispatched micro-batches (1.0 = every padded slot carried a real
    request);
  * `qps` is completed requests over the stats object's lifetime;
  * `compiles` counts engine program compilations — a warmed server
    must hold this constant (the zero-recompile acceptance gate).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional


class ServeStats:
    """Thread-safe serving counters.  See module docstring."""

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._latencies: deque = deque(maxlen=max(int(latency_window), 1))
        # admission / completion
        self.submitted = 0
        self.completed = 0
        self.failed = 0          # engine/batch errors surfaced to requests
        self.expired = 0         # deadline passed before dispatch
        self.shed = 0            # admission rejected (queue full / fault)
        self.queue_depth = 0     # gauge: requests waiting right now
        # batching
        self.batches = 0
        self.batched_requests = 0
        self.batch_slots = 0     # sum of bucket batch sizes dispatched
        # engine
        self.compiles = 0
        self.reloads = 0
        self.reload_failures = 0   # restore raised → kept old params
        self.reloads_refused = 0   # nothing newer / unhealthy walk-back

    # -- mutation ----------------------------------------------------------
    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge(self, field: str, value: int) -> None:
        with self._lock:
            setattr(self, field, value)

    def observe_batch(self, requests: int, slots: int) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += requests
            self.batch_slots += slots

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self.completed += 1
            self._latencies.append(seconds)

    # -- reads -------------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        """Seconds at quantile `q` over the recent-completion reservoir
        (nearest-rank), or None before any completion."""
        with self._lock:
            lats = sorted(self._latencies)
        if not lats:
            return None
        idx = min(int(q * len(lats)), len(lats) - 1)
        return lats[idx]

    def occupancy(self) -> Optional[float]:
        with self._lock:
            if self.batch_slots == 0:
                return None
            return self.batched_requests / self.batch_slots

    def qps(self) -> float:
        with self._lock:
            dt = time.monotonic() - self._t0
            return self.completed / dt if dt > 0 else 0.0

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view for /stats and BENCH_pr5.json."""
        p50, p95 = (self.latency_quantile(0.50),
                    self.latency_quantile(0.95))
        occ = self.occupancy()
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "expired": self.expired,
                "shed": self.shed,
                "queue_depth": self.queue_depth,
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "batch_slots": self.batch_slots,
                "compiles": self.compiles,
                "reloads": self.reloads,
                "reload_failures": self.reload_failures,
                "reloads_refused": self.reloads_refused,
            }
        out["qps"] = round(self.qps(), 3)
        out["p50_latency_ms"] = (round(p50 * 1e3, 3)
                                 if p50 is not None else None)
        out["p95_latency_ms"] = (round(p95 * 1e3, 3)
                                 if p95 is not None else None)
        out["batch_occupancy"] = (round(occ, 4) if occ is not None
                                  else None)
        return out
