"""Serving-tier counters — the inference-side sibling of
`data.pipeline.PipelineStats`.

One `ServeStats` instance is shared by the `InferenceEngine` (compile /
reload accounting), the `MicroBatcher` (admission / batching / latency),
and the `InferenceServer` (the /stats endpoint).  All mutation goes
through the lock; `snapshot()` is the single read surface, so the HTTP
handler, the bench smoke, and tests all see the same semantics:

  * latency quantiles (p50/p95) come from a bounded reservoir of the
    most recent completions — a serving dashboard number, not an exact
    all-time percentile;
  * `occupancy` is real requests / bucket batch slots averaged over
    dispatched micro-batches (1.0 = every padded slot carried a real
    request);
  * `qps` is completed requests over the stats object's lifetime
    (decays on an idle server — a health dashboard should read
    `qps_recent`, completions within the last `qps_window_s` seconds,
    next to `uptime_s`);
  * `compiles` counts engine program compilations — a warmed server
    must hold this constant (the zero-recompile acceptance gate);
  * `observe_request` splits each completion's total latency into
    queue-wait vs service time and records generated tokens + tok/s
    (p50/p95 of each in `snapshot()`) — the attribution a bare
    end-to-end percentile can't give;
  * `observe_cb_step` feeds the continuous-batching occupancy pair:
    `cb_slot_occupancy` (active slots / compiled slots, averaged over
    scheduler steps) and `cb_block_utilization` (KV blocks in use /
    pool size).

`register_into(registry)` additionally exposes every snapshot field
through an `obs.MetricsRegistry` pull-time collector (the /metrics
Prometheus endpoint) without changing any of the above.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from .tenancy import TenantCounts


class ServeStats:
    """Thread-safe serving counters.  See module docstring."""

    def __init__(self, latency_window: int = 2048,
                 qps_window_s: float = 30.0):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # per-tenant engine-level accounting (serve/tenancy.py):
        # bounded-cardinality labels, exported as singa_tenant_* by
        # register_into.  Callers pass registry-FOLDED labels.
        self.tenants = TenantCounts(
            ("submitted", "completed", "shed"))
        self._latencies: deque = deque(maxlen=max(int(latency_window), 1))
        # the total-latency split (observe_request): time in queue
        # before dispatch/admission vs time being served, plus the
        # per-request generated-token count and tok/s — the
        # attribution BENCH_pr5's bare p50/p95 gap was missing
        self._queue_waits: deque = deque(
            maxlen=max(int(latency_window), 1))
        self._services: deque = deque(maxlen=max(int(latency_window), 1))
        self._tok_rates: deque = deque(maxlen=max(int(latency_window), 1))
        # completion timestamps for the windowed QPS (bounded: at most
        # latency_window recent completions contribute)
        self.qps_window_s = max(float(qps_window_s), 0.001)
        self._completions: deque = deque(
            maxlen=max(int(latency_window), 1))
        # timestamped reservoirs for the windowed() view (autoscaler
        # control inputs): (stamp, latency) per completion, stamps per
        # shed
        self._timed_lats: deque = deque(
            maxlen=max(int(latency_window), 1))
        self._shed_t: deque = deque(maxlen=max(int(latency_window), 1))
        # (stamp, active_slots) per scheduler step: the lifetime
        # cb_slot_occupancy average can't fall after the scheduler
        # idles (no steps, no new samples), so the autoscaler reads
        # occupancy over a trailing window instead
        self._cb_t: deque = deque(maxlen=8192)
        # admission / completion
        self.submitted = 0
        self.completed = 0
        self.failed = 0          # engine/batch errors surfaced to requests
        self.expired = 0         # deadline passed before dispatch
        self.expired_on_arrival = 0  # dead on arrival: never queued,
                                     # never prefilled — zero engine
                                     # steps burned (serve/qos.py)
        self.cancelled = 0       # cancelled by the caller (hedge loser)
        self.shed = 0            # admission rejected (queue full / fault)
        # per-class brownout accounting (every class shed also counts
        # in `shed`; these split it by priority)
        self.shed_interactive = 0
        self.shed_batch = 0
        self.shed_best_effort = 0
        self.rejected = 0        # never-servable request (fast 400)
        self.resumed = 0         # admissions that re-entered with a
                                 # resume_from prefix (stream failover)
        self.queue_depth = 0     # gauge: requests waiting right now
        self.generated_tokens = 0
        # continuous batching (serve/scheduler.py)
        self.cb_steps = 0             # scheduler iterations run
        self.cb_active_slot_steps = 0  # sum of active slots per step
        self.cb_block_use_steps = 0    # sum of blocks in use per step
        self.cb_slot_capacity = 0      # gauge: compiled slot count S
        self.cb_blocks_total = 0       # gauge: usable pool blocks
        self.cb_blocks_in_use = 0      # gauge: blocks held right now
        # batching
        self.batches = 0
        self.batched_requests = 0
        self.batch_slots = 0     # sum of bucket batch sizes dispatched
        # gauge: dispatched batches failed in a row (reset by any
        # successful batch) — the wedged-engine signal /healthz
        # degrades on once it crosses ServeSpec.degraded_after
        self.consecutive_batch_failures = 0
        # engine
        self.compiles = 0
        self.reloads = 0
        self.reload_failures = 0   # restore raised → kept old params
        self.reloads_refused = 0   # nothing newer / unhealthy walk-back
        self.torn_polls = 0        # poll raced a live writer → no change
        self.reload_poll_deaths = 0  # poll daemon died on an
                                     # unexpected exception (restarted
                                     # under Backoff; /healthz degrades
                                     # on a persistent streak)
        # real Prometheus histograms (cumulative buckets + _sum/_count)
        # created by register_into(); None until then so the hot path
        # costs one attribute check when /metrics is not wired
        self._hist_latency = None
        self._hist_queue_wait = None
        self._hist_service = None

    # -- mutation ----------------------------------------------------------
    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
            if field == "shed":
                self._shed_t.extend([time.monotonic()] * n)

    def gauge(self, field: str, value: int) -> None:
        with self._lock:
            # a typo'd field must fail loudly (AttributeError), not
            # silently create a new attribute no snapshot ever reads —
            # the same implicit validation count()'s getattr performs
            getattr(self, field)
            setattr(self, field, value)

    def observe_batch(self, requests: int, slots: int) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += requests
            self.batch_slots += slots
            self.consecutive_batch_failures = 0

    def observe_batch_failure(self) -> None:
        with self._lock:
            self.consecutive_batch_failures += 1

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self.completed += 1
            self._latencies.append(seconds)
            now = time.monotonic()
            self._completions.append(now)
            self._timed_lats.append((now, seconds))
        if self._hist_latency is not None:
            self._hist_latency.observe(float(seconds))

    def observe_request(self, queue_wait_s: float, service_s: float,
                        ntokens: int) -> None:
        """Attribute one completed request: time queued before
        dispatch vs time being served, and its generated-token count
        (tok/s recorded when both are positive).  Called next to
        `observe_latency` by both the MicroBatcher and the
        ContinuousScheduler."""
        with self._lock:
            self._queue_waits.append(max(queue_wait_s, 0.0))
            self._services.append(max(service_s, 0.0))
            self.generated_tokens += int(ntokens)
            if ntokens > 0 and service_s > 0:
                self._tok_rates.append(ntokens / service_s)
        if self._hist_queue_wait is not None:
            self._hist_queue_wait.observe(max(float(queue_wait_s), 0.0))
        if self._hist_service is not None:
            self._hist_service.observe(max(float(service_s), 0.0))

    def observe_cb_step(self, active_slots: int,
                        blocks_in_use: int) -> None:
        with self._lock:
            self.cb_steps += 1
            self.cb_active_slot_steps += int(active_slots)
            self.cb_block_use_steps += int(blocks_in_use)
            self._cb_t.append((time.monotonic(), int(active_slots)))

    # -- reads -------------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        """Seconds at quantile `q` (p50/p95/p99 in snapshot) over the
        recent-completion reservoir (nearest-rank), or None before any
        completion."""
        with self._lock:
            lats = sorted(self._latencies)
        if not lats:
            return None
        idx = min(int(q * len(lats)), len(lats) - 1)
        return lats[idx]

    def split_quantile(self, kind: str, q: float) -> Optional[float]:
        """Nearest-rank quantile over one of the observe_request
        reservoirs: kind in ("queue_wait", "service",
        "tokens_per_s")."""
        src = {"queue_wait": self._queue_waits,
               "service": self._services,
               "tokens_per_s": self._tok_rates}[kind]
        with self._lock:
            vals = sorted(src)
        if not vals:
            return None
        return vals[min(int(q * len(vals)), len(vals) - 1)]

    def cb_slot_occupancy(self) -> Optional[float]:
        """Active slots / compiled slots averaged over scheduler
        steps (the cb sibling of `occupancy`)."""
        with self._lock:
            if self.cb_steps == 0 or self.cb_slot_capacity == 0:
                return None
            return self.cb_active_slot_steps / (
                self.cb_steps * self.cb_slot_capacity)

    def cb_slot_occupancy_recent(
            self, window_s: float = 5.0) -> Optional[float]:
        """TIME-weighted slot occupancy over the trailing window:
        slot-seconds actually spent decoding / (window x capacity).
        The per-step lifetime average is wrong twice for a scale-down
        signal — it never falls once the scheduler idles (no steps, no
        new samples), and a scheduler that only steps while busy
        averages high even at 1 rps.  Here the gaps BETWEEN steps
        count as idle time (per-step credit capped at 0.25s so a
        stalled scheduler can't bank a giant interval), so this reads
        ~1.0 under saturation and decays toward 0.0 within `window_s`
        of the last request.  None before any cb step (cb off or not
        yet warmed)."""
        now = time.monotonic()
        with self._lock:
            if self.cb_steps == 0 or self.cb_slot_capacity == 0:
                return None
            window = min(float(window_s), max(now - self._t0, 1e-6))
            cutoff = now - window
            entries = [(t, a) for t, a in self._cb_t if t >= cutoff]
            capacity = self.cb_slot_capacity
        if not entries:
            return 0.0
        busy = 0.0
        prev = cutoff
        for t, a in entries:
            busy += a * min(max(t - prev, 0.0), 0.25)
            prev = t
        return min(busy / (window * capacity), 1.0)

    def cb_block_utilization(self) -> Optional[float]:
        with self._lock:
            if self.cb_steps == 0 or self.cb_blocks_total == 0:
                return None
            return self.cb_block_use_steps / (
                self.cb_steps * self.cb_blocks_total)

    def occupancy(self) -> Optional[float]:
        with self._lock:
            if self.batch_slots == 0:
                return None
            return self.batched_requests / self.batch_slots

    def qps(self) -> float:
        with self._lock:
            dt = time.monotonic() - self._t0
            return self.completed / dt if dt > 0 else 0.0

    def uptime_s(self) -> float:
        return time.monotonic() - self._t0

    def qps_recent(self) -> float:
        """Completions within the last `qps_window_s` seconds over
        that window (capped at uptime while the server is younger than
        the window) — 0.0 the moment traffic stops, where the lifetime
        `qps` only decays asymptotically."""
        now = time.monotonic()
        with self._lock:
            window = min(self.qps_window_s, max(now - self._t0, 1e-6))
            cutoff = now - window
            n = sum(1 for t in self._completions if t >= cutoff)
        return n / window

    def windowed(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """Rates over the trailing window (default `qps_window_s`,
        capped at uptime) — the engine-level sibling of
        `RouterStats.windowed()`.  shed_rate is sheds over admission
        attempts (sheds + completions) inside the window."""
        now = time.monotonic()
        with self._lock:
            window = float(window_s if window_s is not None
                           else self.qps_window_s)
            window = min(window, max(now - self._t0, 1e-6))
            cut = now - window
            shed = sum(1 for t in self._shed_t if t >= cut)
            lats = sorted(l for t, l in self._timed_lats if t >= cut)

        def q(frac):
            if not lats:
                return None
            return round(
                lats[min(int(frac * len(lats)), len(lats) - 1)] * 1e3, 3)
        return {
            "window_s": round(window, 3),
            "completed": len(lats),
            "shed": shed,
            "qps": round(len(lats) / window, 3),
            "shed_rate": round(shed / max(shed + len(lats), 1), 4),
            "p50_latency_ms": q(0.5),
            "p95_latency_ms": q(0.95),
            "p99_latency_ms": q(0.99),
        }

    def register_into(self, registry,
                      prefix: str = "singa_serve") -> None:
        """Register every snapshot field into an `obs.MetricsRegistry`
        as a pull-time collector (counters for the monotonic tallies,
        gauges for the derived/point-in-time values) — additive;
        snapshot() semantics are untouched, so /metrics and /stats
        agree by construction."""
        from ..obs.metrics import Sample

        counters = ("submitted", "completed", "failed", "expired",
                    "expired_on_arrival", "cancelled", "shed",
                    "shed_interactive", "shed_batch",
                    "shed_best_effort", "rejected", "resumed",
                    "generated_tokens", "batches",
                    "batched_requests", "batch_slots", "cb_steps",
                    "compiles", "reloads", "reload_failures",
                    "reloads_refused", "torn_polls",
                    "reload_poll_deaths")
        gauges = ("queue_depth", "consecutive_batch_failures", "qps",
                  "qps_recent", "uptime_s", "p50_latency_ms",
                  "p95_latency_ms", "p99_latency_ms",
                  "shed_rate_recent", "p95_latency_recent_ms",
                  "p99_latency_recent_ms", "p50_queue_wait_ms",
                  "p95_queue_wait_ms", "p50_service_ms",
                  "p95_service_ms", "p50_tokens_per_s",
                  "p95_tokens_per_s", "batch_occupancy",
                  "cb_slot_occupancy", "cb_slot_occupancy_recent",
                  "cb_block_utilization",
                  "cb_blocks_in_use", "cb_blocks_total")

        def collect():
            snap = self.snapshot()
            out = [Sample(f"{prefix}_{k}_total", "counter",
                          f"serving counter {k!r}", float(snap[k]))
                   for k in counters]
            out += [Sample(f"{prefix}_{k}", "gauge",
                           f"serving gauge {k!r}", float(snap[k]))
                    for k in gauges if snap.get(k) is not None]
            return out

        registry.register_collector(collect)
        # per-tenant labeled series (bounded cardinality — see
        # tenancy.TenantCounts); engine-level registries never collide
        # with the router's because each server owns its own registry
        self.tenants.register_into(registry)
        # real histograms (cumulative le buckets + _sum/_count) next
        # to the reservoir quantiles: the reservoir gives honest
        # recent p50/p95, the histogram aggregates across scrapes and
        # fleet members the way Prometheus expects
        self._hist_latency = registry.histogram(
            f"{prefix}_request_latency_seconds",
            "end-to-end request latency on this engine")
        self._hist_queue_wait = registry.histogram(
            f"{prefix}_queue_wait_seconds",
            "time queued before dispatch/admission")
        self._hist_service = registry.histogram(
            f"{prefix}_service_seconds",
            "time being served after dispatch")

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view for /stats and BENCH_pr5.json."""
        p50, p95, p99 = (self.latency_quantile(0.50),
                         self.latency_quantile(0.95),
                         self.latency_quantile(0.99))
        occ = self.occupancy()
        cb_occ = self.cb_slot_occupancy()
        cb_occ_recent = self.cb_slot_occupancy_recent()
        cb_util = self.cb_block_utilization()
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "expired": self.expired,
                "expired_on_arrival": self.expired_on_arrival,
                "cancelled": self.cancelled,
                "shed": self.shed,
                "shed_interactive": self.shed_interactive,
                "shed_batch": self.shed_batch,
                "shed_best_effort": self.shed_best_effort,
                "rejected": self.rejected,
                "resumed": self.resumed,
                "queue_depth": self.queue_depth,
                "generated_tokens": self.generated_tokens,
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "batch_slots": self.batch_slots,
                "cb_steps": self.cb_steps,
                "cb_blocks_in_use": self.cb_blocks_in_use,
                "cb_blocks_total": self.cb_blocks_total,
                "consecutive_batch_failures":
                    self.consecutive_batch_failures,
                "compiles": self.compiles,
                "reloads": self.reloads,
                "reload_failures": self.reload_failures,
                "reloads_refused": self.reloads_refused,
                "torn_polls": self.torn_polls,
                "reload_poll_deaths": self.reload_poll_deaths,
            }
        out["qps"] = round(self.qps(), 3)
        out["qps_recent"] = round(self.qps_recent(), 3)
        win = self.windowed()
        out["shed_rate_recent"] = win["shed_rate"]
        out["p95_latency_recent_ms"] = win["p95_latency_ms"]
        out["p99_latency_recent_ms"] = win["p99_latency_ms"]
        out["uptime_s"] = round(self.uptime_s(), 3)
        out["p50_latency_ms"] = (round(p50 * 1e3, 3)
                                 if p50 is not None else None)
        out["p95_latency_ms"] = (round(p95 * 1e3, 3)
                                 if p95 is not None else None)
        out["p99_latency_ms"] = (round(p99 * 1e3, 3)
                                 if p99 is not None else None)
        for kind, label in (("queue_wait", "queue_wait_ms"),
                            ("service", "service_ms")):
            for q, pre in ((0.50, "p50"), (0.95, "p95")):
                v = self.split_quantile(kind, q)
                out[f"{pre}_{label}"] = (round(v * 1e3, 3)
                                         if v is not None else None)
        for q, pre in ((0.50, "p50"), (0.95, "p95")):
            v = self.split_quantile("tokens_per_s", q)
            out[f"{pre}_tokens_per_s"] = (round(v, 3)
                                          if v is not None else None)
        out["batch_occupancy"] = (round(occ, 4) if occ is not None
                                  else None)
        out["cb_slot_occupancy"] = (round(cb_occ, 4)
                                    if cb_occ is not None else None)
        out["cb_slot_occupancy_recent"] = (
            round(cb_occ_recent, 4)
            if cb_occ_recent is not None else None)
        out["cb_block_utilization"] = (round(cb_util, 4)
                                       if cb_util is not None else None)
        out["by_tenant"] = self.tenants.snapshot()
        return out
