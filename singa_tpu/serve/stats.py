"""Serving-tier counters — the inference-side sibling of
`data.pipeline.PipelineStats`.

One `ServeStats` instance is shared by the `InferenceEngine` (compile /
reload accounting), the `MicroBatcher` (admission / batching / latency),
and the `InferenceServer` (the /stats endpoint).  All mutation goes
through the lock; `snapshot()` is the single read surface, so the HTTP
handler, the bench smoke, and tests all see the same semantics:

  * latency quantiles (p50/p95) come from a bounded reservoir of the
    most recent completions — a serving dashboard number, not an exact
    all-time percentile;
  * `occupancy` is real requests / bucket batch slots averaged over
    dispatched micro-batches (1.0 = every padded slot carried a real
    request);
  * `qps` is completed requests over the stats object's lifetime
    (decays on an idle server — a health dashboard should read
    `qps_recent`, completions within the last `qps_window_s` seconds,
    next to `uptime_s`);
  * `compiles` counts engine program compilations — a warmed server
    must hold this constant (the zero-recompile acceptance gate);
  * `observe_request` splits each completion's total latency into
    queue-wait vs service time and records generated tokens + tok/s
    (p50/p95 of each in `snapshot()`) — the attribution a bare
    end-to-end percentile can't give;
  * `observe_cb_step` feeds the continuous-batching occupancy pair:
    `cb_slot_occupancy` (active slots / compiled slots, averaged over
    scheduler steps) and `cb_block_utilization` (KV blocks in use /
    pool size).

`register_into(registry)` additionally exposes every snapshot field
through an `obs.MetricsRegistry` pull-time collector (the /metrics
Prometheus endpoint) without changing any of the above.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional


class ServeStats:
    """Thread-safe serving counters.  See module docstring."""

    def __init__(self, latency_window: int = 2048,
                 qps_window_s: float = 30.0):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._latencies: deque = deque(maxlen=max(int(latency_window), 1))
        # the total-latency split (observe_request): time in queue
        # before dispatch/admission vs time being served, plus the
        # per-request generated-token count and tok/s — the
        # attribution BENCH_pr5's bare p50/p95 gap was missing
        self._queue_waits: deque = deque(
            maxlen=max(int(latency_window), 1))
        self._services: deque = deque(maxlen=max(int(latency_window), 1))
        self._tok_rates: deque = deque(maxlen=max(int(latency_window), 1))
        # completion timestamps for the windowed QPS (bounded: at most
        # latency_window recent completions contribute)
        self.qps_window_s = max(float(qps_window_s), 0.001)
        self._completions: deque = deque(
            maxlen=max(int(latency_window), 1))
        # admission / completion
        self.submitted = 0
        self.completed = 0
        self.failed = 0          # engine/batch errors surfaced to requests
        self.expired = 0         # deadline passed before dispatch
        self.shed = 0            # admission rejected (queue full / fault)
        self.rejected = 0        # never-servable request (fast 400)
        self.queue_depth = 0     # gauge: requests waiting right now
        self.generated_tokens = 0
        # continuous batching (serve/scheduler.py)
        self.cb_steps = 0             # scheduler iterations run
        self.cb_active_slot_steps = 0  # sum of active slots per step
        self.cb_block_use_steps = 0    # sum of blocks in use per step
        self.cb_slot_capacity = 0      # gauge: compiled slot count S
        self.cb_blocks_total = 0       # gauge: usable pool blocks
        self.cb_blocks_in_use = 0      # gauge: blocks held right now
        # batching
        self.batches = 0
        self.batched_requests = 0
        self.batch_slots = 0     # sum of bucket batch sizes dispatched
        # gauge: dispatched batches failed in a row (reset by any
        # successful batch) — the wedged-engine signal /healthz
        # degrades on once it crosses ServeSpec.degraded_after
        self.consecutive_batch_failures = 0
        # engine
        self.compiles = 0
        self.reloads = 0
        self.reload_failures = 0   # restore raised → kept old params
        self.reloads_refused = 0   # nothing newer / unhealthy walk-back
        self.torn_polls = 0        # poll raced a live writer → no change

    # -- mutation ----------------------------------------------------------
    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge(self, field: str, value: int) -> None:
        with self._lock:
            # a typo'd field must fail loudly (AttributeError), not
            # silently create a new attribute no snapshot ever reads —
            # the same implicit validation count()'s getattr performs
            getattr(self, field)
            setattr(self, field, value)

    def observe_batch(self, requests: int, slots: int) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += requests
            self.batch_slots += slots
            self.consecutive_batch_failures = 0

    def observe_batch_failure(self) -> None:
        with self._lock:
            self.consecutive_batch_failures += 1

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self.completed += 1
            self._latencies.append(seconds)
            self._completions.append(time.monotonic())

    def observe_request(self, queue_wait_s: float, service_s: float,
                        ntokens: int) -> None:
        """Attribute one completed request: time queued before
        dispatch vs time being served, and its generated-token count
        (tok/s recorded when both are positive).  Called next to
        `observe_latency` by both the MicroBatcher and the
        ContinuousScheduler."""
        with self._lock:
            self._queue_waits.append(max(queue_wait_s, 0.0))
            self._services.append(max(service_s, 0.0))
            self.generated_tokens += int(ntokens)
            if ntokens > 0 and service_s > 0:
                self._tok_rates.append(ntokens / service_s)

    def observe_cb_step(self, active_slots: int,
                        blocks_in_use: int) -> None:
        with self._lock:
            self.cb_steps += 1
            self.cb_active_slot_steps += int(active_slots)
            self.cb_block_use_steps += int(blocks_in_use)

    # -- reads -------------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        """Seconds at quantile `q` over the recent-completion reservoir
        (nearest-rank), or None before any completion."""
        with self._lock:
            lats = sorted(self._latencies)
        if not lats:
            return None
        idx = min(int(q * len(lats)), len(lats) - 1)
        return lats[idx]

    def split_quantile(self, kind: str, q: float) -> Optional[float]:
        """Nearest-rank quantile over one of the observe_request
        reservoirs: kind in ("queue_wait", "service",
        "tokens_per_s")."""
        src = {"queue_wait": self._queue_waits,
               "service": self._services,
               "tokens_per_s": self._tok_rates}[kind]
        with self._lock:
            vals = sorted(src)
        if not vals:
            return None
        return vals[min(int(q * len(vals)), len(vals) - 1)]

    def cb_slot_occupancy(self) -> Optional[float]:
        """Active slots / compiled slots averaged over scheduler
        steps (the cb sibling of `occupancy`)."""
        with self._lock:
            if self.cb_steps == 0 or self.cb_slot_capacity == 0:
                return None
            return self.cb_active_slot_steps / (
                self.cb_steps * self.cb_slot_capacity)

    def cb_block_utilization(self) -> Optional[float]:
        with self._lock:
            if self.cb_steps == 0 or self.cb_blocks_total == 0:
                return None
            return self.cb_block_use_steps / (
                self.cb_steps * self.cb_blocks_total)

    def occupancy(self) -> Optional[float]:
        with self._lock:
            if self.batch_slots == 0:
                return None
            return self.batched_requests / self.batch_slots

    def qps(self) -> float:
        with self._lock:
            dt = time.monotonic() - self._t0
            return self.completed / dt if dt > 0 else 0.0

    def uptime_s(self) -> float:
        return time.monotonic() - self._t0

    def qps_recent(self) -> float:
        """Completions within the last `qps_window_s` seconds over
        that window (capped at uptime while the server is younger than
        the window) — 0.0 the moment traffic stops, where the lifetime
        `qps` only decays asymptotically."""
        now = time.monotonic()
        with self._lock:
            window = min(self.qps_window_s, max(now - self._t0, 1e-6))
            cutoff = now - window
            n = sum(1 for t in self._completions if t >= cutoff)
        return n / window

    def register_into(self, registry,
                      prefix: str = "singa_serve") -> None:
        """Register every snapshot field into an `obs.MetricsRegistry`
        as a pull-time collector (counters for the monotonic tallies,
        gauges for the derived/point-in-time values) — additive;
        snapshot() semantics are untouched, so /metrics and /stats
        agree by construction."""
        from ..obs.metrics import Sample

        counters = ("submitted", "completed", "failed", "expired",
                    "shed", "rejected", "generated_tokens", "batches",
                    "batched_requests", "batch_slots", "cb_steps",
                    "compiles", "reloads", "reload_failures",
                    "reloads_refused", "torn_polls")
        gauges = ("queue_depth", "consecutive_batch_failures", "qps",
                  "qps_recent", "uptime_s", "p50_latency_ms",
                  "p95_latency_ms", "p50_queue_wait_ms",
                  "p95_queue_wait_ms", "p50_service_ms",
                  "p95_service_ms", "p50_tokens_per_s",
                  "p95_tokens_per_s", "batch_occupancy",
                  "cb_slot_occupancy", "cb_block_utilization",
                  "cb_blocks_in_use", "cb_blocks_total")

        def collect():
            snap = self.snapshot()
            out = [Sample(f"{prefix}_{k}_total", "counter",
                          f"serving counter {k!r}", float(snap[k]))
                   for k in counters]
            out += [Sample(f"{prefix}_{k}", "gauge",
                           f"serving gauge {k!r}", float(snap[k]))
                    for k in gauges if snap.get(k) is not None]
            return out

        registry.register_collector(collect)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view for /stats and BENCH_pr5.json."""
        p50, p95 = (self.latency_quantile(0.50),
                    self.latency_quantile(0.95))
        occ = self.occupancy()
        cb_occ = self.cb_slot_occupancy()
        cb_util = self.cb_block_utilization()
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "expired": self.expired,
                "shed": self.shed,
                "rejected": self.rejected,
                "queue_depth": self.queue_depth,
                "generated_tokens": self.generated_tokens,
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "batch_slots": self.batch_slots,
                "cb_steps": self.cb_steps,
                "cb_blocks_in_use": self.cb_blocks_in_use,
                "cb_blocks_total": self.cb_blocks_total,
                "consecutive_batch_failures":
                    self.consecutive_batch_failures,
                "compiles": self.compiles,
                "reloads": self.reloads,
                "reload_failures": self.reload_failures,
                "reloads_refused": self.reloads_refused,
                "torn_polls": self.torn_polls,
            }
        out["qps"] = round(self.qps(), 3)
        out["qps_recent"] = round(self.qps_recent(), 3)
        out["uptime_s"] = round(self.uptime_s(), 3)
        out["p50_latency_ms"] = (round(p50 * 1e3, 3)
                                 if p50 is not None else None)
        out["p95_latency_ms"] = (round(p95 * 1e3, 3)
                                 if p95 is not None else None)
        for kind, label in (("queue_wait", "queue_wait_ms"),
                            ("service", "service_ms")):
            for q, pre in ((0.50, "p50"), (0.95, "p95")):
                v = self.split_quantile(kind, q)
                out[f"{pre}_{label}"] = (round(v * 1e3, 3)
                                         if v is not None else None)
        for q, pre in ((0.50, "p50"), (0.95, "p95")):
            v = self.split_quantile("tokens_per_s", q)
            out[f"{pre}_tokens_per_s"] = (round(v, 3)
                                          if v is not None else None)
        out["batch_occupancy"] = (round(occ, 4) if occ is not None
                                  else None)
        out["cb_slot_occupancy"] = (round(cb_occ, 4)
                                    if cb_occ is not None else None)
        out["cb_block_utilization"] = (round(cb_util, 4)
                                       if cb_util is not None else None)
        return out
