"""Metrics registry: counters, gauges, histograms, and pull-time
collectors, rendered as Prometheus text exposition format.

Two ways in:

  * **owned metrics** — `registry.counter("name")` returns a live
    Counter the caller increments.  Creation is idempotent (same name
    + same type returns the same object), so hot paths can cache the
    handle once.
  * **collectors** — `registry.register_collector(fn)` where `fn()`
    returns an iterable of `Sample` tuples read at scrape time.  This
    is how the four existing stat surfaces (`TimerInfo`,
    `PipelineStats`, `ServeStats`, `HealthMonitor`) join the registry
    WITHOUT any change to their own APIs or snapshot semantics: each
    grows an additive `register_into(registry)` that closes over its
    instance and maps its existing snapshot fields to samples.  A
    collector that raises is skipped (and counted in
    `collector_errors`) — a broken stat surface must not take down
    /metrics.

`render_prometheus()` emits `# HELP` / `# TYPE` / sample lines; names
are sanitized to the Prometheus charset (dots and dashes become
underscores).  `snapshot()` returns the same data as a flat dict for
the JSONL event-log exporter on the training side.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple


class Sample(NamedTuple):
    """One scrape-time sample from a collector.

    `labels` is an optional tuple of (key, value) pairs rendered as
    `name{key="value",...}`.  Samples sharing a name (differing only
    in labels) render one HELP/TYPE header followed by every series —
    how `singa_compiles_total{program=...}` fans out per program."""
    name: str
    mtype: str          # "counter" | "gauge" | "histogram"(owned only)
    help: str
    value: float
    labels: Tuple[Tuple[str, str], ...] = ()


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


#: default histogram buckets: latency-ish, seconds
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each
    `le`-bucket counts observations <= its bound, plus +Inf)."""

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name, self.help = name, help
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. +Inf, sum, count) — the raw
        (non-cumulative) counts; rendering accumulates."""
        with self._lock:
            return list(self._counts), self._sum, self._count


def sanitize(name: str) -> str:
    """Map an arbitrary metric name onto the Prometheus charset."""
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() or ch in "_:":
            out.append(ch)
        else:
            out.append("_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s or "_"


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    """Render a Sample's label pairs as `{k="v",...}` (empty string
    when unlabeled).  Values are escaped per the exposition format."""
    if not labels:
        return ""
    parts = []
    for k, v in labels:
        s = str(v).replace("\\", "\\\\").replace('"', '\\"')
        s = s.replace("\n", "\\n")
        parts.append(f'{sanitize(str(k))}="{s}"')
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    if v != v:          # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """See module docstring.  Instances are independent — the serving
    tier builds one per server so tests never cross-pollute; the
    training side's Observability session owns one."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []
        self.collector_errors = 0

    # -- owned metrics ------------------------------------------------------
    def _get(self, name: str, help: str, cls, **kw):
        with self._lock:
            got = self._metrics.get(name)
            if got is not None:
                if not isinstance(got, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(got).__name__}, not {cls.__name__}")
                return got
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, help, Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, help, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get(name, help, Histogram, buckets=buckets)

    # -- collectors ---------------------------------------------------------
    def register_collector(self,
                           fn: Callable[[], Iterable[Sample]]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def _collect(self) -> List[Sample]:
        with self._lock:
            collectors = list(self._collectors)
        out: List[Sample] = []
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:  # noqa: BLE001 — a broken surface must
                self.collector_errors += 1    # not take down /metrics
        return out

    # -- render -------------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        lines: List[str] = []
        with self._lock:
            owned = list(self._metrics.values())
        for m in owned:
            name = sanitize(m.name)
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            if isinstance(m, Histogram):
                lines.append(f"# TYPE {name} histogram")
                counts, total, n = m.snapshot()
                acc = 0
                for b, c in zip(m.buckets, counts):
                    acc += c
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(b)}"}} {acc}')
                acc += counts[-1]
                lines.append(f'{name}_bucket{{le="+Inf"}} {acc}')
                lines.append(f"{name}_sum {_fmt(total)}")
                lines.append(f"{name}_count {n}")
            else:
                kind = ("counter" if isinstance(m, Counter) else
                        "gauge")
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name} {_fmt(m.value)}")
        headed = set()
        for s in self._collect():
            name = sanitize(s.name)
            if name not in headed:       # one HELP/TYPE per name even
                headed.add(name)         # when labels fan out series
                if s.help:
                    lines.append(f"# HELP {name} {s.help}")
                lines.append(f"# TYPE {name} {s.mtype}")
            labels = _label_str(getattr(s, "labels", ()))
            lines.append(f"{name}{labels} {_fmt(s.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, float]:
        """Flat {name: value} view (owned + collected) for the JSONL
        metrics exporter.  Histograms contribute `_sum`/`_count`."""
        out: Dict[str, float] = {}
        with self._lock:
            owned = list(self._metrics.values())
        for m in owned:
            name = sanitize(m.name)
            if isinstance(m, Histogram):
                _, total, n = m.snapshot()
                out[name + "_sum"] = total
                out[name + "_count"] = n
            else:
                out[name] = m.value
        for s in self._collect():
            labels = _label_str(getattr(s, "labels", ()))
            out[sanitize(s.name) + labels] = s.value
        return out


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal parser for the text exposition format — enough for
    tests and the smoke script to assert /metrics agrees with /stats.
    Returns {sample_name_with_labels: value}; raises ValueError on a
    line that is neither a comment nor `name[{labels}] value`."""
    out: Dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            raise ValueError(f"bad exposition line {lineno}: {line!r}")
        name, val = parts
        base = name.split("{", 1)[0]
        if not base or not all(c.isalnum() or c in "_:" for c in base):
            raise ValueError(f"bad metric name at line {lineno}: "
                             f"{name!r}")
        try:
            out[name] = float(val)
        except ValueError as e:
            raise ValueError(f"bad value at line {lineno}: "
                             f"{val!r}") from e
    return out
