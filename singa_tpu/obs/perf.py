"""Performance observatory: compile-time, memory, and per-program
cost accounting.

The ROADMAP names compile-time a first-class cost, but until now the
system could not see the thing it needs to optimize: compiles, HBM,
and per-program FLOPs were all unmeasured.  This module is the
measurement layer — three watchers folded into one process-global
`PerfWatch`:

  * **CompileWatch** — every `jit(...).lower(...).compile()` site
    (engine warmup buckets, cb prefill/decode, the trainer's fused
    scan, the convergence tool) runs inside `compile_span(program,
    geometry, scope)`, recording duration into a
    `singa_compile_seconds` histogram and per-program
    `singa_compiles_total{program=...}` counters; executable-cache
    hits on the engine fast path land in
    `singa_compile_cache_total{program=...,result=...}`.  Scopes model
    PR 8's "zero recompiles after warmup" invariant at runtime: each
    engine marks its scope warm per mode family at the end of
    `warmup()`, and any later compile in a warm (scope, family) is an
    anomaly — counted, emitted as a `perf.recompile_anomaly` event,
    and (via the flight-recorder's trigger table) dumped as evidence.
  * **MemoryWatch** — per-device live/peak HBM gauges from jax
    `memory_stats()` where the backend exposes them, with an analytic
    fallback built from registered components (param bytes, optimizer
    state bytes, PagedKVCache pool bytes from block geometry).  A
    high-watermark gauge tracks the worst total ever observed and is
    surfaced both in /metrics and in flight-recorder dumps.
  * **CostWatch** — harvests XLA `cost_analysis()` FLOPs/bytes from
    ALREADY-COMPILED executables (`utils/flops.cost_metrics`; never
    triggers a compile) into per-program FLOPs, bytes-accessed, and
    arithmetic-intensity gauges, plus MFU when `utils/flops.py`'s
    peak table knows the device (TPU; omitted on CPU).

Cold-start readiness rides along: `mark_serving_ready()` /
`mark_training_ready()` are first-call-wins latches measuring process
start (from /proc where available) to first warm token / first
completed train dispatch, exported as
`singa_restart_to_serving_seconds` / `singa_restart_to_training_seconds`.

Everything here is host-side bookkeeping in the nanosecond-to-
microsecond range, always on (like ServeStats counters), and — like
every obs surface — never raises into the work it measures.  The
module-level functions delegate to a swappable singleton so tests and
benches can `perf.reset()` for a clean slate; `register_into()`
registers a thunk that re-reads the singleton, so registries survive
resets.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .metrics import Histogram, Sample

#: compile durations run 100ms..minutes, not the request-latency
#: range DEFAULT_BUCKETS covers
COMPILE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0)

#: per-program compile records kept for snapshots/dumps
MAX_RECORDS = 256


def _process_start_monotonic() -> float:
    """Monotonic timestamp of process birth.  On Linux, derived from
    /proc so readiness timers measure from exec() even when this
    module imports late; elsewhere, import time is the best anchor
    available."""
    try:
        with open("/proc/self/stat") as f:
            # field 22 (starttime) counts clock ticks after the
            # parenthesised comm field, which may itself contain spaces
            fields = f.read().rsplit(")", 1)[1].split()
        start_ticks = float(fields[19])
        hz = float(os.sysconf("SC_CLK_TCK"))
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        age = uptime - start_ticks / hz
        if age >= 0:
            return time.monotonic() - age
    except Exception:  # noqa: BLE001 — non-Linux / hardened /proc
        pass
    return time.monotonic()


_PROCESS_START = _process_start_monotonic()


def _tree_bytes(tree) -> int:
    """Total array bytes in a pytree of jax/numpy arrays (leaves
    without shape/dtype — python scalars — count 0)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * int(np.dtype(dtype).itemsize)
    return total


class PerfWatch:
    """Compile/memory/cost accounting for one process; see module
    docstring.  All mutators take one short lock; `collect()` is the
    scrape-time reader every registered MetricsRegistry shares."""

    def __init__(self):
        self._lock = threading.Lock()
        # CompileWatch
        self.compile_hist = Histogram(
            "singa_compile_seconds",
            "XLA compile durations across all programs",
            buckets=COMPILE_BUCKETS)
        self._compiles: Dict[str, int] = {}          # program -> count
        self._cache: Dict[Tuple[str, str], int] = {}  # (program, hit|miss)
        self._records: List[Dict[str, Any]] = []
        self._warm: set = set()                      # (scope, family)
        self.anomalies = 0
        # readiness latches (seconds since process start, first win)
        self._serving_ready_s: Optional[float] = None
        self._training_ready_s: Optional[float] = None
        # MemoryWatch: (scope, component) -> bytes; watermark = worst
        # total ever observed across set_memory calls and scrapes
        self._memory: Dict[Tuple[str, str], int] = {}
        self._watermark = 0
        # CostWatch: program -> {"flops":…, "bytes":…, "step_seconds":…}
        self._cost: Dict[str, Dict[str, float]] = {}

    # -- CompileWatch -------------------------------------------------------
    @contextmanager
    def compile_span(self, program: str, geometry: str = "",
                     scope: str = "", family: str = ""):
        """Time one real compile.  `scope` identifies the owner whose
        warmup contract applies (one per engine); `family` is the mode
        family the warmup promise covers (defaults to `program`), so
        e.g. a first `predict` compile after a generate-only warmup is
        lazy, not anomalous."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._record_compile(program, geometry, scope,
                                 family or program,
                                 time.perf_counter() - t0)

    def _record_compile(self, program, geometry, scope, family,
                        seconds) -> None:
        with self._lock:
            self._compiles[program] = self._compiles.get(program, 0) + 1
            key = (program, "miss")
            self._cache[key] = self._cache.get(key, 0) + 1
            anomalous = bool(scope) and (scope, family) in self._warm
            if anomalous:
                self.anomalies += 1
            rec = {"program": program, "geometry": geometry,
                   "scope": scope, "seconds": round(seconds, 6),
                   "anomaly": anomalous}
            self._records.append(rec)
            del self._records[:-MAX_RECORDS]
        self.compile_hist.observe(seconds)
        if anomalous:
            # routes to the event log AND the flight recorder, whose
            # trigger table dumps the evidence window (rate-limited)
            try:
                from singa_tpu import obs
                obs.emit_event("perf.recompile_anomaly",
                               program=program, geometry=geometry,
                               scope=scope,
                               compile_seconds=round(seconds, 6))
            except Exception:  # noqa: BLE001 — telemetry never kills
                pass

    def lookup_hit(self, program: str) -> None:
        """Count an executable-cache hit on a compile fast path."""
        with self._lock:
            key = (program, "hit")
            self._cache[key] = self._cache.get(key, 0) + 1

    def mark_warm(self, scope: str, family: str = "") -> None:
        """Declare `scope`'s warmup promise for `family`; compiles
        after this in the same (scope, family) are anomalies.  A
        family warmed per mode keeps lazily-compiled OTHER modes
        (e.g. first `predict` after a generate-only warmup) from
        reading as violations."""
        with self._lock:
            self._warm.add((scope, family))

    def is_warm(self, scope: str, family: str = "") -> bool:
        with self._lock:
            return (scope, family) in self._warm

    def compiles_total(self) -> int:
        with self._lock:
            return sum(self._compiles.values())

    # -- readiness ----------------------------------------------------------
    def _latch(self, attr: str) -> float:
        with self._lock:
            got = getattr(self, attr)
            if got is None:
                got = max(time.monotonic() - _PROCESS_START, 1e-9)
                setattr(self, attr, got)
            return got

    def mark_serving_ready(self) -> float:
        """Latch process-start → first warm token (first call wins)."""
        return self._latch("_serving_ready_s")

    def mark_training_ready(self) -> float:
        """Latch process-start → first completed train dispatch."""
        return self._latch("_training_ready_s")

    @property
    def serving_ready_s(self) -> Optional[float]:
        return self._serving_ready_s

    @property
    def training_ready_s(self) -> Optional[float]:
        return self._training_ready_s

    # -- MemoryWatch --------------------------------------------------------
    def set_memory(self, component: str, nbytes: int,
                   scope: str = "") -> None:
        """Register/refresh one analytic HBM component (train_params,
        opt_state, serve_params, kv_pool).  Components are keyed per
        scope so a trainer and an engine in one process don't clobber
        each other."""
        with self._lock:
            self._memory[(scope, component)] = max(int(nbytes), 0)
            total = sum(self._memory.values())
            if total > self._watermark:
                self._watermark = total

    def set_memory_tree(self, component: str, tree,
                        scope: str = "") -> int:
        """`set_memory` from a pytree of arrays; returns the bytes."""
        try:
            nbytes = _tree_bytes(tree)
        except Exception:  # noqa: BLE001
            return 0
        self.set_memory(component, nbytes, scope=scope)
        return nbytes

    def device_memory(self) -> List[Dict[str, Any]]:
        """Live/peak bytes per local device from jax `memory_stats()`;
        empty on backends that expose none (CPU)."""
        out: List[Dict[str, Any]] = []
        try:
            import jax
            for i, d in enumerate(jax.local_devices()):
                stats = d.memory_stats()
                if not stats:
                    continue
                out.append({
                    "device": i,
                    "kind": getattr(d, "device_kind", "?"),
                    "live": int(stats.get("bytes_in_use", 0)),
                    "peak": int(stats.get("peak_bytes_in_use", 0)),
                })
        except Exception:  # noqa: BLE001
            return out
        return out

    # -- CostWatch ----------------------------------------------------------
    def harvest(self, program: str, compiled) -> Dict[str, float]:
        """Pull FLOPs/bytes off an already-compiled executable (no
        compile is ever triggered — see utils/flops.cost_metrics).
        Merges into the program's cost entry and returns it."""
        from ..utils.flops import cost_metrics
        ca = cost_metrics(compiled)
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed", ca.get("bytes_accessed"))
        with self._lock:
            entry = self._cost.setdefault(program, {})
            if flops and flops > 0:
                entry["flops"] = float(flops)
            if nbytes and nbytes > 0:
                entry["bytes"] = float(nbytes)
            return dict(entry)

    def observe_step(self, program: str, seconds: float) -> None:
        """Record the latest wall time of one execution of `program`
        so MFU (flops / (step · peak)) can be derived at scrape."""
        if seconds <= 0:
            return
        with self._lock:
            self._cost.setdefault(program, {})["step_seconds"] = \
                float(seconds)

    # -- export -------------------------------------------------------------
    def collect(self) -> List[Sample]:
        """Scrape-time samples for MetricsRegistry collectors."""
        from ..utils.flops import mfu, peak_flops
        with self._lock:
            compiles = dict(self._compiles)
            cache = dict(self._cache)
            anomalies = self.anomalies
            memory = dict(self._memory)
            cost = {k: dict(v) for k, v in self._cost.items()}
            serving = self._serving_ready_s
            training = self._training_ready_s
        out: List[Sample] = []
        for program, n in sorted(compiles.items()):
            out.append(Sample(
                "singa_compiles_total", "counter",
                "XLA compiles per program", float(n),
                (("program", program),)))
        for (program, result), n in sorted(cache.items()):
            out.append(Sample(
                "singa_compile_cache_total", "counter",
                "executable cache lookups per program", float(n),
                (("program", program), ("result", result))))
        _, hsum, hcount = self.compile_hist.snapshot()
        out.append(Sample("singa_compile_seconds_sum", "counter",
                          "total seconds spent compiling", hsum))
        out.append(Sample("singa_compile_seconds_count", "counter",
                          "total compiles timed", float(hcount)))
        out.append(Sample("singa_recompile_anomalies_total", "counter",
                          "post-warmup compiles (PR 8 invariant "
                          "violations)", float(anomalies)))
        if serving is not None:
            out.append(Sample("singa_restart_to_serving_seconds",
                              "gauge", "process start to first warm "
                              "token", serving))
        if training is not None:
            out.append(Sample("singa_restart_to_training_seconds",
                              "gauge", "process start to first "
                              "completed train dispatch", training))
        # memory: real device stats when the backend has them, plus
        # the analytic components and their watermark (the fallback —
        # and the only signal on CPU)
        live_total = 0
        peak_total = 0
        for dm in self.device_memory():
            dev = (("device", str(dm["device"])),
                   ("kind", dm["kind"]))
            out.append(Sample("singa_hbm_live_bytes", "gauge",
                              "device bytes in use", float(dm["live"]),
                              dev))
            out.append(Sample("singa_hbm_peak_bytes", "gauge",
                              "device peak bytes in use",
                              float(dm["peak"]), dev))
            live_total += dm["live"]
            peak_total += dm["peak"]
        analytic_total = 0
        by_component: Dict[str, int] = {}
        for (_scope, component), nbytes in memory.items():
            by_component[component] = (by_component.get(component, 0)
                                       + nbytes)
            analytic_total += nbytes
        for component, nbytes in sorted(by_component.items()):
            out.append(Sample("singa_hbm_analytic_bytes", "gauge",
                              "analytic HBM model per component",
                              float(nbytes),
                              (("component", component),)))
        out.append(Sample("singa_hbm_analytic_total_bytes", "gauge",
                          "sum of analytic HBM components",
                          float(analytic_total)))
        with self._lock:
            # scrapes can raise the watermark too (device peak counts)
            observed = max(analytic_total, live_total, peak_total)
            if observed > self._watermark:
                self._watermark = observed
            watermark = self._watermark
        out.append(Sample("singa_hbm_watermark_bytes", "gauge",
                          "high-watermark of observed/modelled HBM",
                          float(watermark)))
        # cost: flops/bytes/arithmetic intensity per program; MFU only
        # when the peak table knows this device (None on CPU)
        try:
            peak = peak_flops()
        except Exception:  # noqa: BLE001
            peak = None
        for program, entry in sorted(cost.items()):
            lab = (("program", program),)
            flops = entry.get("flops")
            nbytes = entry.get("bytes")
            step = entry.get("step_seconds")
            if flops:
                out.append(Sample("singa_program_flops", "gauge",
                                  "XLA cost-analysis FLOPs per "
                                  "execution", flops, lab))
            if nbytes:
                out.append(Sample("singa_program_bytes", "gauge",
                                  "XLA cost-analysis bytes accessed "
                                  "per execution", nbytes, lab))
            if flops and nbytes:
                out.append(Sample("singa_program_arith_intensity",
                                  "gauge", "FLOPs per byte accessed",
                                  flops / nbytes, lab))
            if flops and step and peak:
                got = mfu(flops, step)
                if got is not None:
                    out.append(Sample("singa_program_mfu", "gauge",
                                      "achieved FLOPs over device "
                                      "peak", got, lab))
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Structured view for benches and flight-recorder dumps."""
        with self._lock:
            _, hsum, hcount = self.compile_hist.snapshot()
            by_component: Dict[str, int] = {}
            for (_s, component), nbytes in self._memory.items():
                by_component[component] = (
                    by_component.get(component, 0) + nbytes)
            return {
                "compiles": dict(self._compiles),
                "compiles_total": sum(self._compiles.values()),
                "cache": {f"{p}:{r}": n
                          for (p, r), n in self._cache.items()},
                "compile_seconds_sum": round(hsum, 6),
                "compile_count": hcount,
                "anomalies": self.anomalies,
                "records": list(self._records[-32:]),
                "serving_ready_s": self._serving_ready_s,
                "training_ready_s": self._training_ready_s,
                "memory_components": by_component,
                "hbm_watermark_bytes": self._watermark,
                "cost": {k: dict(v) for k, v in self._cost.items()},
            }

    def flightrec_context(self) -> Dict[str, Any]:
        """Small additive context for flight-recorder dumps: memory
        state and readiness — the numbers a post-mortem asks first."""
        snap = self.snapshot()
        return {"hbm_watermark_bytes": snap["hbm_watermark_bytes"],
                "memory_components": snap["memory_components"],
                "serving_ready_s": snap["serving_ready_s"],
                "training_ready_s": snap["training_ready_s"],
                "compiles_total": snap["compiles_total"],
                "anomalies": snap["anomalies"]}


# -- process-level collector (satellite: every /metrics endpoint) ----------

def process_samples() -> List[Sample]:
    """RSS, thread count, open fds, uptime, CPU time for this process
    — the collector that makes a leaking engine visible.  Registered
    on every MetricsRegistry (trainer session, engine, fleet,
    pipeline).  Sources degrade gracefully off-Linux."""
    out: List[Sample] = []
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        out.append(Sample("singa_process_rss_bytes", "gauge",
                          "resident set size",
                          float(rss_pages * os.sysconf("SC_PAGE_SIZE"))))
    except Exception:  # noqa: BLE001
        pass
    out.append(Sample("singa_process_threads", "gauge",
                      "live python threads",
                      float(threading.active_count())))
    try:
        out.append(Sample("singa_process_open_fds", "gauge",
                          "open file descriptors",
                          float(len(os.listdir("/proc/self/fd")))))
    except Exception:  # noqa: BLE001
        pass
    out.append(Sample("singa_process_uptime_seconds", "gauge",
                      "seconds since process start",
                      max(time.monotonic() - _PROCESS_START, 0.0)))
    try:
        t = os.times()
        out.append(Sample("singa_process_cpu_seconds_total", "counter",
                          "user+system CPU seconds",
                          float(t.user + t.system)))
    except Exception:  # noqa: BLE001
        pass
    return out


def register_process_into(registry) -> None:
    """Register the process-level collector on `registry`."""
    registry.register_collector(process_samples)


# -- module-level singleton API --------------------------------------------

_WATCH = PerfWatch()


def watch() -> PerfWatch:
    """The process-global PerfWatch."""
    return _WATCH


def reset() -> PerfWatch:
    """Swap in a fresh PerfWatch (tests/benches).  Registries wired
    via `register_into` keep working: their collector re-reads the
    singleton at every scrape."""
    global _WATCH
    _WATCH = PerfWatch()
    return _WATCH


def register_into(registry) -> None:
    """Register the perf collector (reset-proof) on `registry`."""
    registry.register_collector(lambda: _WATCH.collect())


def compile_span(program: str, geometry: str = "", scope: str = "",
                 family: str = ""):
    return _WATCH.compile_span(program, geometry=geometry,
                               scope=scope, family=family)


def lookup_hit(program: str) -> None:
    _WATCH.lookup_hit(program)


def mark_warm(scope: str, family: str = "") -> None:
    _WATCH.mark_warm(scope, family)


def mark_serving_ready() -> float:
    return _WATCH.mark_serving_ready()


def mark_training_ready() -> float:
    return _WATCH.mark_training_ready()


def set_memory(component: str, nbytes: int, scope: str = "") -> None:
    _WATCH.set_memory(component, nbytes, scope=scope)


def set_memory_tree(component: str, tree, scope: str = "") -> int:
    return _WATCH.set_memory_tree(component, tree, scope=scope)


def harvest(program: str, compiled) -> Dict[str, float]:
    return _WATCH.harvest(program, compiled)


def observe_step(program: str, seconds: float) -> None:
    _WATCH.observe_step(program, seconds)


def snapshot() -> Dict[str, Any]:
    return _WATCH.snapshot()


def flightrec_context() -> Dict[str, Any]:
    return _WATCH.flightrec_context()
