"""Structured event log (JSONL) and the component logger.

`EventLog` appends one JSON object per line — the machine-readable
sibling of the human log: supervisor restarts/rescues, health
verdicts, reload outcomes, shed counts, and periodic metrics
snapshots all land here as `{"ts": ..., "kind": ..., ...}` records a
dashboard (or the smoke script) can grep without parsing prose.
Every write consults the `obs.emit` fault site and swallows any
failure into `dropped` — a full disk or an injected telemetry fault
drops events, never a training step or a request.

`Logger` is the `obs.log` satellite: a callable drop-in for the
`log_fn=print` plumbing that already threads through Trainer /
Supervisor / CheckpointManager / the serve tier.  It prefixes
`[component]`, infers the level from the established `"warning: ..."`
convention (so existing messages keep their meaning), writes warnings
and errors to stderr, and mirrors warning+ lines into the active
session's event log.  Default output stays human-readable — the
smoke scripts' greps keep matching.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, TextIO

from ..utils import faults

LEVELS = ("debug", "info", "warning", "error")


class EventLog:
    """Append-only JSONL event sink; see module docstring.

    `max_bytes > 0` bounds the file: when the next line would cross
    the bound, the current file rotates to `<path>.1` (one previous
    generation, overwritten each rotation — disk stays under ~2x the
    bound for a week-long pipeline run) and a fresh file is opened.
    The `written`/`dropped` counters are CUMULATIVE across rotations:
    the flush accounting (`obs.flush` event) must keep adding up no
    matter how many times the file rolled underneath it."""

    def __init__(self, path: str, max_bytes: int = 0):
        import os
        self.path = path
        self.max_bytes = max(int(max_bytes or 0), 0)
        self._lock = threading.Lock()
        self.written = 0
        self.dropped = 0
        self.rotations = 0
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f: Optional[TextIO] = open(path, "a")
        try:
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0

    def _rotate_locked(self) -> None:
        """Roll the live file to `<path>.1` and reopen.  Caller holds
        the lock; any failure propagates to emit()'s drop counter."""
        import os
        self._f.close()
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "a")
        self._size = 0
        self.rotations += 1

    def emit(self, kind: str, **fields) -> bool:
        """Append one event.  Returns False (drop counted) on any
        failure — injected `obs.emit` faults included."""
        try:
            faults.maybe_fault("obs.emit")
            rec: Dict[str, Any] = {"ts": round(time.time(), 6),
                                   "kind": kind}
            rec.update(fields)
            line = json.dumps(rec, default=str, sort_keys=False)
            with self._lock:
                if self._f is None:
                    raise ValueError("event log closed")
                if (self.max_bytes and self._size > 0
                        and self._size + len(line) + 1
                        > self.max_bytes):
                    self._rotate_locked()
                self._f.write(line + "\n")
                self._f.flush()
                self._size += len(line) + 1
                self.written += 1
            return True
        except Exception:  # noqa: BLE001 — telemetry never kills work
            self.dropped += 1
            return False

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except Exception:  # noqa: BLE001
                    pass
                self._f = None


class Logger:
    """Component logger, callable like the `log_fn` it replaces.

    `logger("msg")` infers the level ("warning: ..." → warning, else
    info); `.debug/.info/.warning/.error` set it explicitly.  Output
    format is `[component] msg` on stdout (warning+ on stderr) via
    `sink` — pass `sink` to capture output in tests exactly as a bare
    log_fn would be.  `event_log_for` is resolved per call so a
    logger built at import time starts mirroring warning+ records the
    moment a session is enabled."""

    def __init__(self, component: str,
                 sink: Optional[Callable[..., None]] = None,
                 event_log_for: Optional[
                     Callable[[], Optional[EventLog]]] = None):
        self.component = component
        self._sink = sink
        self._event_log_for = event_log_for

    def __call__(self, msg: str) -> None:
        text = str(msg)
        low = text.lstrip().lower()
        if low.startswith("warning:"):
            self.log("warning", text)
        elif low.startswith("error:"):
            self.log("error", text)
        else:
            self.log("info", text)

    def debug(self, msg: str) -> None:
        self.log("debug", msg)

    def info(self, msg: str) -> None:
        self.log("info", msg)

    def warning(self, msg: str) -> None:
        self.log("warning", msg)

    def error(self, msg: str) -> None:
        self.log("error", msg)

    def log(self, level: str, msg: str) -> None:
        text = f"[{self.component}] {msg}"
        if self._sink is not None:
            self._sink(text)
        elif level in ("warning", "error"):
            print(text, file=sys.stderr)
        else:
            print(text)
        if level in ("warning", "error") and \
                self._event_log_for is not None:
            ev = self._event_log_for()
            if ev is not None:
                ev.emit("log", level=level, component=self.component,
                        msg=str(msg))


class MetricsDumper:
    """Daemon thread dumping a registry snapshot into the event log
    every `period_s` — the training side's periodic exporter (the
    serve tier is pull-based via /metrics instead)."""

    def __init__(self, registry, event_log: EventLog,
                 period_s: float):
        self._registry = registry
        self._events = event_log
        self._period = max(float(period_s), 0.05)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="obs-metrics",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._period):
            self._dump()

    def _dump(self) -> None:
        try:
            snap = self._registry.snapshot()
        except Exception:  # noqa: BLE001 — never kill the dumper
            return
        self._events.emit("metrics", metrics=snap)

    def stop(self, final_dump: bool = True) -> None:
        self._stop.set()
        self._thread.join(2.0)
        if final_dump:
            self._dump()
