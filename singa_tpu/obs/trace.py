"""Span tracer: thread-safe, ~zero-cost-when-off context-manager
spans exporting Chrome trace-event JSON.

Design targets (docs/OBSERVABILITY.md):

  * **~zero cost off** — instrumented code calls `obs.span(name)`,
    which is one module-global read plus returning a shared null
    context manager when no observability session is active (the same
    discipline as `faults.maybe_fault`).
  * **parenting** — each thread keeps a span stack; a new span's
    parent is the innermost open span on the SAME thread, recorded as
    `args.parent_id`.  Remote and cross-thread parents are explicit:
    `span(name, trace=..., parent=...)` anchors a span under a parent
    from another process (the `X-Trace-Id`/`X-Parent-Span` header
    pair) or another thread (a captured `context()` tuple).
  * **trace ids** — every root span mints a trace id; children (and
    explicitly-anchored remote spans) inherit it, so one request's
    spans across router threads, hedge legs, and worker processes all
    carry the same `args.trace` and a merged file groups by it.
  * **correlation ids** — a span either carries an explicit `corr`
    (e.g. `req-3`, `batch-7`, `attempt-2`) or inherits its parent's.
    Cross-thread flows (DeviceFeeder staging, HTTP handler → dispatch
    thread) pass the corr value explicitly; `current_corr()` reads the
    innermost corr on the calling thread for exactly that hand-off.
  * **telemetry never kills work** — recording a finished span
    consults the `obs.emit` fault site and swallows *any* failure into
    a `dropped` counter; the traced code path sees nothing.

Export format: `{"traceEvents": [...], "displayTimeUnit": "ms"}` with
`ph: "X"` complete events (ts/dur in microseconds) plus `ph: "M"`
thread-name and process-name metadata — the same trace-event schema
`utils/profiler.parse_trace_ops` consumes from device traces, so both
files load side by side in Perfetto / chrome://tracing.  The dict
additionally carries `process`, `pid`, and `wall_origin_s` top-level
keys (legal extras in the Chrome schema): `wall_origin_s` is the
wall-clock instant of this tracer's ts=0, which is what lets
`obs/collect.py` re-anchor buffers from different processes onto one
merged timeline.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..utils import faults


class SpanHandle:
    """The object a `with obs.span(...) as sp` body sees: carries the
    resolved trace/correlation ids and lets the body attach attributes
    that end up in the exported event's `args`."""

    __slots__ = ("name", "span_id", "parent_id", "trace", "corr",
                 "attrs", "_t0")

    def __init__(self, name: str, span_id: int, parent_id: int,
                 trace: str, corr: Optional[str],
                 attrs: Dict[str, Any], t0: float):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace = trace
        self.corr = corr
        self.attrs = attrs
        self._t0 = t0

    def set(self, **kw) -> None:
        self.attrs.update(kw)


class _NullHandle:
    """Shared no-op handle when tracing is off."""
    __slots__ = ()
    name = ""
    span_id = 0
    parent_id = 0
    trace = ""
    corr = None

    def set(self, **kw) -> None:
        pass


NULL_HANDLE = _NullHandle()


class NullSpan:
    """Shared no-op context manager: the entire off-path cost of an
    instrumented site is one global read plus entering this."""
    __slots__ = ()

    def __enter__(self) -> _NullHandle:
        return NULL_HANDLE

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = NullSpan()


class _SpanCtx:
    """One live span.  Class-based (not @contextmanager) to keep the
    on-path overhead at a couple of attribute stores; exceptions in
    the body propagate untouched — the span still records."""

    __slots__ = ("_tracer", "_handle")

    def __init__(self, tracer: "Tracer", handle: SpanHandle):
        self._tracer = tracer
        self._handle = handle

    def __enter__(self) -> SpanHandle:
        self._tracer._push(self._handle)
        return self._handle

    def __exit__(self, exc_type, exc, tb) -> bool:
        h = self._handle
        dur = time.perf_counter() - h._t0
        self._tracer._pop()
        if exc_type is not None:
            h.attrs.setdefault("error", exc_type.__name__)
        self._tracer._record(h, dur)
        return False


class Tracer:
    """Thread-safe span recorder; see module docstring.

    `max_spans` bounds the in-memory buffer — spans past it are
    dropped (counted), never an error.  `ring > 0` switches the
    buffer to a ring of the most RECENT `ring` spans instead (older
    spans are evicted, counted in `evicted`) — the `GET /trace`
    serving mode, where a long-lived worker must always hold its
    freshest window.  `export(path)` writes the Chrome trace JSON;
    `events()` returns the raw event dicts for tests and in-process
    consumers."""

    def __init__(self, max_spans: int = 200_000, ring: int = 0,
                 process: Optional[str] = None):
        self.max_spans = max(int(max_spans), 1)
        self.ring = max(int(ring), 0)
        self.process = process or f"pid-{os.getpid()}"
        self.dropped = 0
        self.evicted = 0
        self.sampled_out = 0
        self._lock = threading.Lock()
        self._events: Any = (deque(maxlen=self.ring) if self.ring
                             else [])
        # span ids must stay unique across PROCESSES for a merged
        # parent_id graph to resolve, so each tracer counts from a
        # random 52-bit-safe base rather than 1
        self._ids = itertools.count(
            (int.from_bytes(os.urandom(4), "big") << 20) + 1)
        # trace ids: one random base per tracer plus a counter — a
        # root span mint is a dict-free string format, not a syscall
        self._trace_base = os.urandom(6).hex()
        self._trace_ids = itertools.count(1)
        self._local = threading.local()
        self._threads_seen: Dict[int, str] = {}
        # perf_counter origin for this tracer: ts values are relative
        # microseconds.  The paired wall-clock instant is what lets a
        # collector line this buffer up against other processes'.
        self._origin = time.perf_counter()
        self._wall_origin = time.time()

    def set_process(self, name: str) -> None:
        """Name this tracer's track in merged traces (engine/worker
        name rather than the bare pid)."""
        self.process = str(name)

    # -- thread-local span stack --------------------------------------------
    def _stack(self) -> List[SpanHandle]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, h: SpanHandle) -> None:
        self._stack().append(h)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def current(self) -> Optional[SpanHandle]:
        """Innermost open span on the calling thread, or None."""
        st = getattr(self._local, "stack", None)
        return st[-1] if st else None

    def current_corr(self) -> Optional[str]:
        cur = self.current()
        return cur.corr if cur is not None else None

    def context(self) -> Optional[Tuple[str, int]]:
        """`(trace_id, span_id)` of the innermost open span on this
        thread — the value to carry across a thread or process hop
        and hand back as `span(..., trace=..., parent=...)`."""
        cur = self.current()
        if cur is None:
            return None
        return (cur.trace, cur.span_id)

    def _mint_trace(self) -> str:
        return f"{self._trace_base}{next(self._trace_ids):08x}"

    # -- span creation ------------------------------------------------------
    def span(self, name: str, corr: Optional[str] = None,
             trace: Optional[str] = None,
             parent: Optional[int] = None, **attrs) -> _SpanCtx:
        """Open a span.  With no explicit anchor, the parent is the
        innermost open span on the calling thread and `corr`/`trace`
        default to its values; a root span mints a fresh trace id.
        `trace`/`parent` anchor the span under a REMOTE parent — the
        receiver side of the `X-Trace-Id`/`X-Parent-Span` hop, or a
        cross-thread hand-off of `context()`."""
        cur = self.current()
        if parent is not None:
            parent_id = int(parent)
        elif cur is not None:
            parent_id = cur.span_id
        else:
            parent_id = 0
        if cur is not None:
            if corr is None:
                corr = cur.corr
            if trace is None:
                trace = cur.trace
        if trace is None:
            trace = self._mint_trace()
        handle = SpanHandle(name, next(self._ids), parent_id, trace,
                            corr, attrs, time.perf_counter())
        return _SpanCtx(self, handle)

    def add_span(self, name: str, t0: float, dur_s: float,
                 corr: Optional[str] = None,
                 trace: Optional[str] = None,
                 parent: Optional[int] = None, **attrs) -> int:
        """Record an already-measured span (`t0` in perf_counter
        seconds) without entering a context manager — the shape the
        router uses for stream stages it can only time across
        generator yields.  Returns the span id (0 on drop)."""
        h = SpanHandle(name, next(self._ids),
                       int(parent) if parent is not None else 0,
                       trace if trace is not None
                       else self._mint_trace(),
                       corr, attrs, t0)
        self._record(h, dur_s)
        return h.span_id

    # -- recording ----------------------------------------------------------
    def _record(self, h: SpanHandle, dur_s: float) -> None:
        try:
            faults.maybe_fault("obs.emit")
            tid = threading.get_ident()
            args: Dict[str, Any] = {"span_id": h.span_id,
                                    "trace": h.trace}
            if h.parent_id:
                args["parent_id"] = h.parent_id
            if h.corr is not None:
                args["corr"] = h.corr
            for k, v in h.attrs.items():
                args[k] = v if isinstance(v, (int, float, str, bool,
                                              type(None))) else str(v)
            ev = {"ph": "X", "cat": "obs", "name": h.name,
                  "pid": os.getpid(), "tid": tid,
                  "ts": round((h._t0 - self._origin) * 1e6, 3),
                  "dur": round(dur_s * 1e6, 3),
                  "args": args}
            with self._lock:
                if self.ring:
                    if len(self._events) == self._events.maxlen:
                        self.evicted += 1
                    self._events.append(ev)
                else:
                    if len(self._events) >= self.max_spans:
                        self.dropped += 1
                        return
                    self._events.append(ev)
                if tid not in self._threads_seen:
                    self._threads_seen[tid] = \
                        threading.current_thread().name
        except Exception:  # noqa: BLE001 — telemetry never kills work
            self.dropped += 1

    def discard_trace(self, trace_id: str) -> int:
        """Tail-based sampling's drop half: remove every buffered
        span of `trace_id`, counting them in `sampled_out`.  Returns
        the number removed."""
        if not trace_id:
            return 0
        with self._lock:
            kept = [e for e in self._events
                    if e["args"].get("trace") != trace_id]
            n = len(self._events) - len(kept)
            if n:
                if self.ring:
                    self._events = deque(kept, maxlen=self.ring)
                else:
                    self._events = kept
                self.sampled_out += n
        return n

    # -- reads / export -----------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def trace_dict(self) -> Dict[str, Any]:
        """The full Chrome trace object (span events + thread/process
        metadata), ready for json.dump or the `GET /trace` wire."""
        with self._lock:
            events = list(self._events)
            threads = dict(self._threads_seen)
        pid = os.getpid()
        meta = [{"ph": "M", "pid": pid, "tid": 0,
                 "name": "process_name",
                 "args": {"name": self.process}}]
        meta += [{"ph": "M", "pid": pid, "tid": tid,
                  "name": "thread_name", "args": {"name": tname}}
                 for tid, tname in sorted(threads.items())]
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "process": self.process, "pid": pid,
                "wall_origin_s": round(self._wall_origin, 6)}

    def export(self, path: str) -> bool:
        """Write the Chrome trace JSON to `path` (parent dirs
        created).  Returns False (and counts a drop) on any failure —
        a full disk must not fail a training run at exit."""
        try:
            faults.maybe_fault("obs.emit")
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.trace_dict(), f)
            os.replace(tmp, path)
            return True
        except Exception:  # noqa: BLE001
            self.dropped += 1
            return False
