"""Span tracer: thread-safe, ~zero-cost-when-off context-manager
spans exporting Chrome trace-event JSON.

Design targets (docs/OBSERVABILITY.md):

  * **~zero cost off** — instrumented code calls `obs.span(name)`,
    which is one module-global read plus returning a shared null
    context manager when no observability session is active (the same
    discipline as `faults.maybe_fault`).
  * **parenting** — each thread keeps a span stack; a new span's
    parent is the innermost open span on the SAME thread, recorded as
    `args.parent_id`.  Perfetto additionally nests by timestamp within
    a (pid, tid) track, so the exported JSON reads as a flame chart
    with no extra work.
  * **correlation ids** — a span either carries an explicit `corr`
    (e.g. `req-3`, `batch-7`, `attempt-2`) or inherits its parent's.
    Cross-thread flows (DeviceFeeder staging, HTTP handler → dispatch
    thread) pass the corr value explicitly; `current_corr()` reads the
    innermost corr on the calling thread for exactly that hand-off.
  * **telemetry never kills work** — recording a finished span
    consults the `obs.emit` fault site and swallows *any* failure into
    a `dropped` counter; the traced code path sees nothing.

Export format: `{"traceEvents": [...], "displayTimeUnit": "ms"}` with
`ph: "X"` complete events (ts/dur in microseconds) plus `ph: "M"`
thread-name metadata — the same trace-event schema
`utils/profiler.parse_trace_ops` consumes from device traces, so both
files load side by side in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import faults


class SpanHandle:
    """The object a `with obs.span(...) as sp` body sees: carries the
    resolved correlation id and lets the body attach attributes that
    end up in the exported event's `args`."""

    __slots__ = ("name", "span_id", "parent_id", "corr", "attrs", "_t0")

    def __init__(self, name: str, span_id: int, parent_id: int,
                 corr: Optional[str], attrs: Dict[str, Any], t0: float):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.corr = corr
        self.attrs = attrs
        self._t0 = t0

    def set(self, **kw) -> None:
        self.attrs.update(kw)


class _NullHandle:
    """Shared no-op handle when tracing is off."""
    __slots__ = ()
    name = ""
    span_id = 0
    parent_id = 0
    corr = None

    def set(self, **kw) -> None:
        pass


NULL_HANDLE = _NullHandle()


class NullSpan:
    """Shared no-op context manager: the entire off-path cost of an
    instrumented site is one global read plus entering this."""
    __slots__ = ()

    def __enter__(self) -> _NullHandle:
        return NULL_HANDLE

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = NullSpan()


class _SpanCtx:
    """One live span.  Class-based (not @contextmanager) to keep the
    on-path overhead at a couple of attribute stores; exceptions in
    the body propagate untouched — the span still records."""

    __slots__ = ("_tracer", "_handle")

    def __init__(self, tracer: "Tracer", handle: SpanHandle):
        self._tracer = tracer
        self._handle = handle

    def __enter__(self) -> SpanHandle:
        self._tracer._push(self._handle)
        return self._handle

    def __exit__(self, exc_type, exc, tb) -> bool:
        h = self._handle
        dur = time.perf_counter() - h._t0
        self._tracer._pop()
        if exc_type is not None:
            h.attrs.setdefault("error", exc_type.__name__)
        self._tracer._record(h, dur)
        return False


class Tracer:
    """Thread-safe span recorder; see module docstring.

    `max_spans` bounds the in-memory buffer — spans past it are
    dropped (counted), never an error.  `export(path)` writes the
    Chrome trace JSON; `events()` returns the raw event dicts for
    tests and in-process consumers."""

    def __init__(self, max_spans: int = 200_000):
        self.max_spans = max(int(max_spans), 1)
        self.dropped = 0
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._threads_seen: Dict[int, str] = {}
        # perf_counter origin for this tracer: ts values are relative
        # microseconds, which is all Perfetto needs for one file
        self._origin = time.perf_counter()

    # -- thread-local span stack --------------------------------------------
    def _stack(self) -> List[SpanHandle]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, h: SpanHandle) -> None:
        self._stack().append(h)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def current(self) -> Optional[SpanHandle]:
        """Innermost open span on the calling thread, or None."""
        st = getattr(self._local, "stack", None)
        return st[-1] if st else None

    def current_corr(self) -> Optional[str]:
        cur = self.current()
        return cur.corr if cur is not None else None

    # -- span creation ------------------------------------------------------
    def span(self, name: str, corr: Optional[str] = None,
             **attrs) -> _SpanCtx:
        """Open a span.  `corr` defaults to the parent span's
        correlation id (same thread); extra keyword args become
        exported `args`."""
        parent = self.current()
        if parent is not None:
            parent_id = parent.span_id
            if corr is None:
                corr = parent.corr
        else:
            parent_id = 0
        handle = SpanHandle(name, next(self._ids), parent_id, corr,
                            attrs, time.perf_counter())
        return _SpanCtx(self, handle)

    # -- recording ----------------------------------------------------------
    def _record(self, h: SpanHandle, dur_s: float) -> None:
        try:
            faults.maybe_fault("obs.emit")
            tid = threading.get_ident()
            args: Dict[str, Any] = {"span_id": h.span_id}
            if h.parent_id:
                args["parent_id"] = h.parent_id
            if h.corr is not None:
                args["corr"] = h.corr
            for k, v in h.attrs.items():
                args[k] = v if isinstance(v, (int, float, str, bool,
                                              type(None))) else str(v)
            ev = {"ph": "X", "cat": "obs", "name": h.name,
                  "pid": os.getpid(), "tid": tid,
                  "ts": round((h._t0 - self._origin) * 1e6, 3),
                  "dur": round(dur_s * 1e6, 3),
                  "args": args}
            with self._lock:
                if len(self._events) >= self.max_spans:
                    self.dropped += 1
                    return
                self._events.append(ev)
                if tid not in self._threads_seen:
                    self._threads_seen[tid] = \
                        threading.current_thread().name
        except Exception:  # noqa: BLE001 — telemetry never kills work
            self.dropped += 1

    # -- reads / export -----------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def trace_dict(self) -> Dict[str, Any]:
        """The full Chrome trace object (span events + thread-name
        metadata), ready for json.dump."""
        with self._lock:
            events = list(self._events)
            threads = dict(self._threads_seen)
        pid = os.getpid()
        meta = [{"ph": "M", "pid": pid, "tid": tid,
                 "name": "thread_name", "args": {"name": tname}}
                for tid, tname in sorted(threads.items())]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export(self, path: str) -> bool:
        """Write the Chrome trace JSON to `path` (parent dirs
        created).  Returns False (and counts a drop) on any failure —
        a full disk must not fail a training run at exit."""
        try:
            faults.maybe_fault("obs.emit")
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.trace_dict(), f)
            os.replace(tmp, path)
            return True
        except Exception:  # noqa: BLE001
            self.dropped += 1
            return False
