"""Fleet trace collector: pull per-process span buffers (`GET
/trace` or in-process `trace_dict()`s) and merge them into ONE
Perfetto-loadable file keyed by trace id.

Each process's tracer timestamps spans in microseconds relative to
its own `perf_counter` origin — meaningless across processes.  Every
buffer therefore carries `wall_origin_s`, the wall-clock instant of
its ts=0; the merge re-anchors every event onto the EARLIEST origin
among the buffers, so a router-side dispatch span and the worker-side
prefill span it caused line up on one timeline (to NTP skew, which is
noise at request granularity).  Span ids are minted from per-process
random bases (trace.py), so parent links resolve unambiguously after
the merge and re-pulling an overlapping buffer window dedupes cleanly
on `(pid, span_id)`.

`critical_path(...)` is the post-mortem read: for one trace id, walk
the span tree from its root and attribute the end-to-end latency to
the stages (and engines) that actually spent it — self time, not
inclusive time, so a parent that merely waited on its child reads as
cheap.  `tools/trace_timeline.py` prints this as text.
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "fetch_trace", "merge", "collect", "trace_ids", "spans_of",
    "orphans", "critical_path",
]


def fetch_trace(base_url: str, timeout: float = 5.0) -> Dict[str, Any]:
    """Pull one worker's span ring: `GET <base_url>/trace`."""
    url = base_url.rstrip("/") + "/trace"
    if not url.startswith("http"):
        url = "http://" + url
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _is_span(ev: Dict[str, Any]) -> bool:
    return ev.get("ph") == "X"


def merge(buffers: Iterable[Dict[str, Any]],
          trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Merge trace dicts from many processes into one, re-anchored
    onto the earliest wall origin, deduped on `(pid, span_id)` (span
    events) / `(pid, tid, name)` (metadata).  `trace_id` keeps only
    that request's spans — metadata rides along either way."""
    buffers = [b for b in buffers if b]
    origins = [b["wall_origin_s"] for b in buffers
               if b.get("wall_origin_s") is not None]
    base = min(origins) if origins else 0.0
    out: List[Dict[str, Any]] = []
    seen_spans = set()
    seen_meta = set()
    processes: Dict[int, str] = {}
    for buf in buffers:
        shift_us = ((buf["wall_origin_s"] - base) * 1e6
                    if buf.get("wall_origin_s") is not None else 0.0)
        pid = buf.get("pid")
        if pid is not None and buf.get("process"):
            processes[int(pid)] = str(buf["process"])
        for ev in buf.get("traceEvents", ()):
            if _is_span(ev):
                args = ev.get("args", {})
                if trace_id is not None and \
                        args.get("trace") != trace_id:
                    continue
                key = (ev.get("pid"), args.get("span_id"))
                if key[1] is not None and key in seen_spans:
                    continue
                seen_spans.add(key)
                ev = dict(ev)
                ev["ts"] = round(float(ev.get("ts", 0.0))
                                 + shift_us, 3)
                out.append(ev)
            elif ev.get("ph") == "M":
                key = (ev.get("pid"), ev.get("tid"), ev.get("name"),
                       str(ev.get("args")))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                out.append(ev)
    # metadata first (Perfetto applies names to subsequent events),
    # spans in timestamp order — the merged file reads chronologically
    meta = [e for e in out if e.get("ph") == "M"]
    spans = sorted((e for e in out if _is_span(e)),
                   key=lambda e: (e.get("ts", 0.0),
                                  e.get("dur", 0.0)))
    return {"traceEvents": meta + spans, "displayTimeUnit": "ms",
            "wall_origin_s": base, "processes": processes}


def collect(urls: Iterable[str], out: Optional[str] = None,
            trace_id: Optional[str] = None, timeout: float = 5.0,
            extra_buffers: Iterable[Dict[str, Any]] = ()
            ) -> Dict[str, Any]:
    """Pull every worker's `/trace` ring (plus any in-process
    buffers, e.g. the router's own `obs.trace_dump()`), merge, and
    optionally write the merged file.  Unreachable workers are
    skipped with a note in the result — a dead engine is often
    exactly why you are collecting."""
    buffers: List[Dict[str, Any]] = list(extra_buffers)
    unreachable: List[str] = []
    for u in urls:
        try:
            buffers.append(fetch_trace(u, timeout=timeout))
        except Exception:  # noqa: BLE001 — collect what is alive
            unreachable.append(str(u))
    merged = merge(buffers, trace_id=trace_id)
    if unreachable:
        merged["unreachable"] = unreachable
    if out:
        d = os.path.dirname(os.path.abspath(out))
        os.makedirs(d, exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, out)
    return merged


def trace_ids(merged: Dict[str, Any]) -> List[str]:
    """Distinct trace ids in first-appearance (timestamp) order."""
    seen: Dict[str, None] = {}
    for ev in merged.get("traceEvents", ()):
        if _is_span(ev):
            t = ev.get("args", {}).get("trace")
            if t is not None and t not in seen:
                seen[t] = None
    return list(seen)


def spans_of(merged: Dict[str, Any],
             trace_id: str) -> List[Dict[str, Any]]:
    """One request's spans, timestamp-ordered."""
    return sorted(
        (ev for ev in merged.get("traceEvents", ())
         if _is_span(ev)
         and ev.get("args", {}).get("trace") == trace_id),
        key=lambda e: (e.get("ts", 0.0), e.get("dur", 0.0)))


def orphans(merged: Dict[str, Any],
            trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Spans whose `parent_id` does not resolve within the merged
    file (optionally restricted to one trace) — a merged fleet trace
    with zero orphans is the proof that every hop re-anchored."""
    evs = (spans_of(merged, trace_id) if trace_id is not None
           else [e for e in merged.get("traceEvents", ())
                 if _is_span(e)])
    ids = {e["args"].get("span_id") for e in evs}
    return [e for e in evs
            if e["args"].get("parent_id")
            and e["args"]["parent_id"] not in ids]


def critical_path(merged: Dict[str, Any],
                  trace_id: str) -> List[Dict[str, Any]]:
    """Attribute one request's latency: every span of the trace with
    its SELF time (duration minus children's overlap with it),
    engine, and process, sorted by self time descending.  The head of
    the list is where the request's wall-clock actually went."""
    evs = spans_of(merged, trace_id)
    if not evs:
        return []
    processes = merged.get("processes", {})
    by_id = {e["args"]["span_id"]: e for e in evs}
    child_time: Dict[Any, float] = {}
    for e in evs:
        pid_ = e["args"].get("parent_id")
        parent = by_id.get(pid_)
        if parent is None:
            continue
        # clip the child's interval to the parent's: a child that
        # outlives its parent (async hand-off) only discounts overlap
        p0, p1 = parent["ts"], parent["ts"] + parent.get("dur", 0.0)
        c0, c1 = e["ts"], e["ts"] + e.get("dur", 0.0)
        overlap = max(0.0, min(p1, c1) - max(p0, c0))
        child_time[pid_] = child_time.get(pid_, 0.0) + overlap
    out = []
    for e in evs:
        args = e["args"]
        dur = float(e.get("dur", 0.0))
        self_us = max(0.0, dur - child_time.get(args["span_id"], 0.0))
        out.append({
            "name": e.get("name"), "ts": e.get("ts"), "dur_us": dur,
            "self_us": round(self_us, 3),
            "engine": args.get("engine"),
            "corr": args.get("corr"),
            "process": processes.get(e.get("pid"),
                                     str(e.get("pid"))),
            "span_id": args["span_id"],
            "parent_id": args.get("parent_id", 0),
        })
    out.sort(key=lambda r: -r["self_us"])
    return out
