"""Post-mortem flight recorder: a bounded in-memory ring of recent
events (plus the tracer's freshest spans) that dumps itself to
`<dir>/flightrec-<trigger>-<n>.json` the moment something goes wrong.

The observability trade at fleet rates is that full tracing is
usually off or tail-sampled — and the one night a canary rolls back
at 3am is exactly the night nobody had `--obs_spec trace=...` set.
The recorder closes that gap: it rides along whenever a session is
active (no trace/events exporters required), costs one deque append
per event, and on a trigger writes the last window of events and
spans so the post-mortem starts from evidence instead of from a bare
exit code.

Triggers (docs/OBSERVABILITY.md has the table):

  * `fleet.rollback` / `fleet.canary_abort`  — a rollout went wrong
  * `fleet.quarantine`                       — an engine was struck out
  * `stream.resume`                          — a mid-stream failover
  * shed storm — `serve.shed` events above `SHED_STORM_N` within
    `SHED_STORM_WINDOW_S` (one shed is load; a storm is an incident)
  * divergence — any event whose `verdict`/`status` reads DIVERGED
  * `obs.flush` fault — the telemetry teardown itself was faulted

Every dump is rate-limited per trigger kind (`cooldown_s`) so a
quarantine flap cannot fill the disk the recorder exists to protect.
Like every other obs write path, a failed dump is counted
(`dump_failures`), never raised.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

#: event kinds that fire a dump, mapped to the dump's trigger label
TRIGGER_KINDS = {
    "fleet.rollback": "rollback",
    "fleet.canary_abort": "rollback",
    "fleet.quarantine": "quarantine",
    "stream.resume": "failover",
    # PR 8's "zero recompiles after warmup" as a monitored invariant:
    # a compile landing in an already-warm scope is an anomaly worth a
    # post-mortem window (what request geometry broke the buckets?)
    "perf.recompile_anomaly": "recompile",
    # a router came back from the dead and replayed its WAL: the
    # recovery evidence (what was journaled, what resumed, what went
    # stale) is exactly what the post-mortem of the crash needs
    "router.recover": "router_restart",
}

#: `serve.shed` events inside the window that constitute a storm
SHED_STORM_N = 16
SHED_STORM_WINDOW_S = 5.0

#: sheds ONE tenant must absorb inside the window for its own storm
#: trigger.  Deliberately below SHED_STORM_N: a tenant's last-N sheds
#: are a subset of history, so with an equal threshold the global
#: window would always trip first and the per-tenant view could never
#: fire.  The per-tenant storm additionally requires DILUTION — other
#: tenants' sheds inside the global window — so a single-tenant burst
#: still reads as the plain `shed_storm` it always was.
SHED_TENANT_STORM_N = 12

#: distinct tenants tracked for the per-tenant storm trigger; excess
#: ids share one "other" window (bounded memory, like singa_tenant_*)
SHED_TENANT_CAP = 64

#: spans pulled from the tracer tail into each dump
DUMP_SPANS = 256


class FlightRecorder:
    """Bounded event ring + trigger-driven dumps; see module
    docstring.  `observe(kind, fields)` is the per-event hot path
    (one lock + deque append + a set lookup); `trigger(why)` forces
    a dump — the `obs.flush` fault path uses it directly."""

    def __init__(self, out_dir: str, ring: int = 512,
                 cooldown_s: float = 5.0, extra_fn=None):
        self.out_dir = out_dir
        self.cooldown_s = max(float(cooldown_s), 0.0)
        # optional () -> dict merged into each dump under "perf" —
        # Observability wires the perf watch's watermark/readiness
        # snapshot here so memory state rides along with the evidence
        self.extra_fn = extra_fn
        self.dumps = 0
        self.dump_failures = 0
        self.sheds_seen = 0
        self._ring: deque = deque(maxlen=max(int(ring), 16))
        self._shed_ts: deque = deque(maxlen=SHED_STORM_N)
        # per-tenant shed windows: one tenant's storm is ITS incident
        # (tenant_shed_storm) even when the global rate stays calm —
        # the blast-radius view of the same signal
        self._shed_ts_by_tenant: Dict[str, deque] = {}
        self._last_dump: Dict[str, float] = {}
        self._seq = itertools.count(1)
        self._lock = threading.Lock()

    def observe(self, kind: str, fields: Dict[str, Any],
                tracer=None) -> Optional[str]:
        """Record one event; dump if it is (or completes) a trigger.
        Returns the dump path when one was written."""
        try:
            rec = {"ts": round(time.time(), 6), "kind": kind}
            for k, v in fields.items():
                rec[k] = v if isinstance(v, (int, float, str, bool,
                                             type(None))) else str(v)
            with self._lock:
                self._ring.append(rec)
            why = TRIGGER_KINDS.get(kind)
            if why is None and kind == "serve.shed":
                why = self._observe_shed(
                    str(fields.get("tenant") or "default"))
            if why is None and str(
                    fields.get("verdict", fields.get("status", ""))
                    ).upper() == "DIVERGED":
                why = "divergence"
            if why is not None:
                return self.trigger(why, tracer=tracer)
            return None
        except Exception:  # noqa: BLE001 — telemetry never kills work
            self.dump_failures += 1
            return None

    def _observe_shed(self, tenant: str = "default") -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            self.sheds_seen += 1
            self._shed_ts.append((now, tenant))
            full = len(self._shed_ts) == self._shed_ts.maxlen
            stormy = (full and now - self._shed_ts[0][0]
                      <= SHED_STORM_WINDOW_S)
            tw = self._shed_ts_by_tenant.get(tenant)
            if tw is None:
                if len(self._shed_ts_by_tenant) >= SHED_TENANT_CAP:
                    tenant = "other"
                tw = self._shed_ts_by_tenant.setdefault(
                    tenant, deque(maxlen=SHED_TENANT_STORM_N))
            tw.append(now)
            # diluted: the global window carries OTHER tenants' sheds
            # too, so the fleet-wide counter under-reads this tenant's
            # burst — exactly the blind spot the per-tenant view fills
            t_stormy = (len(tw) == tw.maxlen
                        and now - tw[0] <= SHED_STORM_WINDOW_S
                        and any(tn != tenant
                                for _, tn in self._shed_ts))
        if stormy:
            return "shed_storm"
        # the fleet-wide storm wins (it subsumes the tenant view)
        return "tenant_shed_storm" if t_stormy else None

    def trigger(self, why: str, tracer=None,
                **context) -> Optional[str]:
        """Dump the ring (rate-limited per `why`).  Returns the path
        written, or None (cooldown / failure — counted, not raised)."""
        try:
            now = time.monotonic()
            with self._lock:
                last = self._last_dump.get(why)
                if last is not None and now - last < self.cooldown_s:
                    return None
                self._last_dump[why] = now
                events = list(self._ring)
                seq = next(self._seq)
            spans = []
            if tracer is not None:
                spans = tracer.events()[-DUMP_SPANS:]
            dump = {"trigger": why, "wall_ts": round(time.time(), 6),
                    "pid": os.getpid(),
                    "process": getattr(tracer, "process", None),
                    "context": context,
                    "events": events, "spans": spans}
            if self.extra_fn is not None:
                try:
                    dump["perf"] = self.extra_fn()
                except Exception:  # noqa: BLE001 — evidence is
                    pass           # best-effort, never a new failure
            os.makedirs(self.out_dir, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in why)
            path = os.path.join(self.out_dir,
                                f"flightrec-{safe}-{seq}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dump, f, default=str)
            os.replace(tmp, path)
            self.dumps += 1
            return path
        except Exception:  # noqa: BLE001
            self.dump_failures += 1
            return None
