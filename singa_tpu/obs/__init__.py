"""Unified observability: span tracing, a metrics registry, a
structured JSONL event log, and component loggers — one layer across
training (Supervisor/Trainer/feeder/checkpoints) and serving
(batcher/engine/server).

The reference's only telemetry was the per-phase timer report
(worker.h:91-114); this package is the cross-cutting read surface the
ROADMAP's remaining items (fleet router health, canary promotion,
pipeline mode) consume.  Four rules:

  1. **~zero cost off.**  `obs.span(...)` / `obs.emit_event(...)` are
     one module-global read when no session is active — the same
     discipline as `faults.maybe_fault`.  Instrumented hot paths pay
     nothing until `--obs on`.
  2. **telemetry never kills work.**  Every record/write path consults
     the `obs.emit` fault site and swallows ALL failures into drop
     counters (`tests/test_obs.py` proves a faulted emit still
     completes the step / the request).
  3. **existing surfaces keep their semantics.**  `TimerInfo`,
     `PipelineStats`, `ServeStats`, `HealthMonitor` register into the
     `MetricsRegistry` through additive `register_into` collectors —
     their own APIs and snapshots are unchanged.
  4. **correlation across tiers.**  Spans inherit their parent's
     correlation id on the same thread; cross-thread hand-offs pass
     `obs.current_corr()` explicitly.  A request flows
     req→batch→engine; a recovery flows attempt→restore→chunks.

CLI: `--obs on|off` plus `--obs_spec 'trace=path,events=path,
metrics_period_s=5'` (main.py), mirroring `--health_spec`.  Artifacts:
a Chrome trace JSON (Perfetto-loadable next to `utils/profiler`
device traces) and a JSONL event log.  See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, fields
from typing import Any, Callable, Dict, Optional

from .log import EventLog, Logger, MetricsDumper
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      Sample, parse_prometheus)
from .trace import NULL_HANDLE, NULL_SPAN, Tracer

__all__ = [
    "ObsSpec", "Observability", "enable", "disable", "active",
    "session", "span", "current_corr", "emit_event", "get_logger",
    "registry", "Tracer", "MetricsRegistry", "Counter", "Gauge",
    "Histogram", "Sample", "EventLog", "Logger", "parse_prometheus",
]


@dataclass
class ObsSpec:
    """`--obs_spec` grammar: comma/semicolon-separated `key=value`
    entries over these fields (the `--health_spec` convention).  Empty
    `trace`/`events` paths disable that exporter; main.py defaults
    both under `<workspace>/obs/` when `--obs on` is given bare."""
    trace: str = ""             # Chrome trace JSON output path
    events: str = ""            # JSONL event log output path
    metrics_period_s: float = 0.0   # >0: periodic metrics → event log
    max_spans: int = 200_000    # in-memory span buffer bound

    _INT = ("max_spans",)
    _STR = ("trace", "events")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "ObsSpec":
        out = cls()
        if not spec:
            return out
        known = {f.name for f in fields(cls)
                 if not f.name.startswith("_")}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep or key not in known:
                raise ValueError(
                    f"bad obs spec entry {part!r} (want key=value "
                    f"with key in {sorted(known)})")
            val = val.strip()
            try:
                if key in cls._STR:
                    setattr(out, key, val)
                elif key in cls._INT:
                    setattr(out, key, int(val))
                else:
                    setattr(out, key, float(val))
            except ValueError as e:
                raise ValueError(
                    f"bad obs spec value for {key!r}: {val!r}") from e
        return out


class Observability:
    """One live session: a tracer, a metrics registry, an optional
    event log, and the periodic metrics dumper.  Built by `enable`,
    torn down (trace exported, log closed) by `disable`."""

    def __init__(self, spec: Optional[ObsSpec] = None):
        self.spec = spec or ObsSpec()
        self.tracer = Tracer(max_spans=self.spec.max_spans)
        self.registry = MetricsRegistry()
        self.events: Optional[EventLog] = (
            EventLog(self.spec.events) if self.spec.events else None)
        self._dumper: Optional[MetricsDumper] = (
            MetricsDumper(self.registry, self.events,
                          self.spec.metrics_period_s)
            if self.events is not None
            and self.spec.metrics_period_s > 0 else None)

    def flush(self) -> None:
        """Export the trace, final-dump metrics, close the event
        log.  Safe to call more than once; never raises."""
        try:
            if self._dumper is not None:
                self._dumper.stop(final_dump=True)
                self._dumper = None
            if self.spec.trace:
                self.tracer.export(self.spec.trace)
            if self.events is not None:
                self.events.emit(
                    "obs.flush",
                    spans=len(self.tracer.events()),
                    spans_dropped=self.tracer.dropped,
                    events_dropped=self.events.dropped)
                self.events.close()
        except Exception:  # noqa: BLE001 — teardown never raises
            pass


_LOCK = threading.Lock()
_ACTIVE: Optional[Observability] = None


def enable(spec: Optional[ObsSpec] = None) -> Observability:
    """Install a process-global session (replacing — and flushing —
    any previous one).  Returns it."""
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, Observability(spec)
    if prev is not None:
        prev.flush()
    return _ACTIVE


def disable() -> None:
    """Flush and remove the active session.  No-op when off."""
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, None
    if prev is not None:
        prev.flush()


def active() -> Optional[Observability]:
    return _ACTIVE


class session:
    """`with obs.session(spec): ...` — enable for the body, flush on
    exit (tests and bench legs)."""

    def __init__(self, spec: Optional[ObsSpec] = None):
        self._spec = spec

    def __enter__(self) -> Observability:
        return enable(self._spec)

    def __exit__(self, *exc) -> bool:
        disable()
        return False


# -- the instrumented-site API (hot-path: one global read when off) ---------

def span(name: str, corr: Optional[str] = None, **attrs):
    """Open a trace span, or the shared null span when off."""
    o = _ACTIVE
    if o is None:
        return NULL_SPAN
    return o.tracer.span(name, corr=corr, **attrs)


def current_corr() -> Optional[str]:
    """Correlation id of the innermost open span on this thread (for
    explicit cross-thread hand-off), or None."""
    o = _ACTIVE
    if o is None:
        return None
    return o.tracer.current_corr()


def emit_event(kind: str, **fields) -> None:
    """Append a structured event to the active session's JSONL log.
    No-op when off or when the session has no events path; any
    failure is swallowed into the log's drop counter."""
    o = _ACTIVE
    if o is not None and o.events is not None:
        o.events.emit(kind, **fields)


def registry() -> Optional[MetricsRegistry]:
    """The active session's metrics registry, or None when off."""
    o = _ACTIVE
    return o.registry if o is not None else None


def get_logger(component: str,
               sink: Optional[Callable[..., None]] = None) -> Logger:
    """A component logger usable anywhere a bare `log_fn` is —
    resolves the active event log per call, so it mirrors warning+
    records whenever a session is live."""
    return Logger(component, sink=sink,
                  event_log_for=lambda: (
                      _ACTIVE.events if _ACTIVE is not None else None))
