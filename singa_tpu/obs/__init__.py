"""Unified observability: span tracing, a metrics registry, a
structured JSONL event log, and component loggers — one layer across
training (Supervisor/Trainer/feeder/checkpoints) and serving
(batcher/engine/server).

The reference's only telemetry was the per-phase timer report
(worker.h:91-114); this package is the cross-cutting read surface the
ROADMAP's remaining items (fleet router health, canary promotion,
pipeline mode) consume.  Four rules:

  1. **~zero cost off.**  `obs.span(...)` / `obs.emit_event(...)` are
     one module-global read when no session is active — the same
     discipline as `faults.maybe_fault`.  Instrumented hot paths pay
     nothing until `--obs on`.
  2. **telemetry never kills work.**  Every record/write path consults
     the `obs.emit` fault site and swallows ALL failures into drop
     counters (`tests/test_obs.py` proves a faulted emit still
     completes the step / the request).
  3. **existing surfaces keep their semantics.**  `TimerInfo`,
     `PipelineStats`, `ServeStats`, `HealthMonitor` register into the
     `MetricsRegistry` through additive `register_into` collectors —
     their own APIs and snapshots are unchanged.
  4. **correlation across tiers AND processes.**  Spans inherit their
     parent's correlation id on the same thread; cross-thread
     hand-offs pass `obs.current_corr()` / `obs.trace_context()`
     explicitly; cross-PROCESS hops carry the trace context as the
     `X-Trace-Id`/`X-Parent-Span` header pair (serve/qos.py) and the
     receiver re-anchors with `obs.span(..., trace=..., parent=...)`.
     A request flows req→batch→engine; a recovery flows
     attempt→restore→chunks; a fleet request flows
     frontend→dispatch→worker with ONE trace id end to end.

CLI: `--obs on|off` plus `--obs_spec 'trace=path,events=path,
metrics_period_s=5'` (main.py), mirroring `--health_spec`.  Artifacts:
a Chrome trace JSON (Perfetto-loadable next to `utils/profiler`
device traces), a JSONL event log, and flight-recorder dumps
(`flightrec.py`).  `collect.py` merges per-process buffers into one
fleet trace.  See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, fields
from typing import Any, Callable, Dict, Optional, Tuple

from . import perf
from .flightrec import FlightRecorder
from .log import EventLog, Logger, MetricsDumper
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      Sample, parse_prometheus)
from .trace import NULL_HANDLE, NULL_SPAN, Tracer

__all__ = [
    "ObsSpec", "Observability", "TailSampler", "enable", "disable",
    "active", "session", "span", "current_corr", "trace_context",
    "trace_dump", "emit_event", "sample_trace", "get_logger",
    "registry", "Tracer", "FlightRecorder", "MetricsRegistry",
    "Counter", "Gauge", "Histogram", "Sample", "EventLog", "Logger",
    "parse_prometheus", "perf",
]


@dataclass
class ObsSpec:
    """`--obs_spec` grammar: comma/semicolon-separated `key=value`
    entries over these fields (the `--health_spec` convention).  Empty
    `trace`/`events` paths disable that exporter; main.py defaults
    both under `<workspace>/obs/` when `--obs on` is given bare."""
    trace: str = ""             # Chrome trace JSON output path
    events: str = ""            # JSONL event log output path
    metrics_period_s: float = 0.0   # >0: periodic metrics → event log
    max_spans: int = 200_000    # in-memory span buffer bound
    max_events_mb: float = 0.0  # >0: rotate the JSONL log at this size
    trace_ring: int = 0         # >0: keep the most recent N spans
                                # instead (the GET /trace serving mode)
    process: str = ""           # process/engine name on merged tracks
    sample: str = "all"         # "all" | "tail" (tail-based sampling)
    sample_slow_ms: float = 0.0     # tail: explicit slow bar; 0 = the
                                    # caller's windowed p95
    flightrec: str = ""         # dir for flightrec-*.json dumps
    flightrec_ring: int = 512   # flight-recorder event ring bound

    _INT = ("max_spans", "trace_ring", "flightrec_ring")
    _STR = ("trace", "events", "process", "sample", "flightrec")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "ObsSpec":
        out = cls()
        if not spec:
            return out
        known = {f.name for f in fields(cls)
                 if not f.name.startswith("_")}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep or key not in known:
                raise ValueError(
                    f"bad obs spec entry {part!r} (want key=value "
                    f"with key in {sorted(known)})")
            val = val.strip()
            try:
                if key in cls._STR:
                    setattr(out, key, val)
                elif key in cls._INT:
                    setattr(out, key, int(val))
                else:
                    setattr(out, key, float(val))
            except ValueError as e:
                raise ValueError(
                    f"bad obs spec value for {key!r}: {val!r}") from e
        if out.sample not in ("all", "tail"):
            raise ValueError(f"bad obs spec value for 'sample': "
                             f"{out.sample!r} (want all|tail)")
        return out


class TailSampler:
    """Tail-based sampling policy (`sample=tail`): keep full traces
    only for INTERESTING requests — slow against the caller-supplied
    windowed p95 (or the explicit `sample_slow_ms` bar), failed, shed,
    hedged, or resumed — and count-then-drop the rest.  With
    `sample=all` every trace is kept and this is pure bookkeeping."""

    def __init__(self, spec: ObsSpec):
        self.spec = spec
        self.kept = 0
        self.sampled_out = 0
        self._lock = threading.Lock()

    def keep(self, latency_s: float, p95_s: Optional[float] = None,
             failed: bool = False, shed: bool = False,
             hedged: bool = False, resumed: bool = False) -> bool:
        interesting = True
        if self.spec.sample == "tail":
            if self.spec.sample_slow_ms > 0:
                bar = self.spec.sample_slow_ms / 1000.0
            else:
                bar = p95_s
            interesting = bool(
                failed or shed or hedged or resumed
                or (bar is not None and latency_s > bar))
        with self._lock:
            if interesting:
                self.kept += 1
            else:
                self.sampled_out += 1
        return interesting

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"policy": self.spec.sample, "kept": self.kept,
                    "sampled_out": self.sampled_out}


class Observability:
    """One live session: a tracer, a metrics registry, an optional
    event log, the periodic metrics dumper, the tail sampler, and an
    optional flight recorder.  Built by `enable`, torn down (trace
    exported, log closed) by `disable`."""

    def __init__(self, spec: Optional[ObsSpec] = None):
        self.spec = spec or ObsSpec()
        self.tracer = Tracer(max_spans=self.spec.max_spans,
                             ring=self.spec.trace_ring,
                             process=self.spec.process or None)
        self.registry = MetricsRegistry()
        # the performance observatory and the process collector ride
        # on every session registry (perf.register_into survives
        # perf.reset(): its collector re-reads the singleton)
        perf.register_into(self.registry)
        perf.register_process_into(self.registry)
        self.sampler = TailSampler(self.spec)
        self.events: Optional[EventLog] = (
            EventLog(self.spec.events,
                     max_bytes=int(self.spec.max_events_mb
                                   * 1024 * 1024))
            if self.spec.events else None)
        self.flightrec: Optional[FlightRecorder] = (
            FlightRecorder(self.spec.flightrec,
                           ring=self.spec.flightrec_ring,
                           extra_fn=perf.flightrec_context)
            if self.spec.flightrec else None)
        self._dumper: Optional[MetricsDumper] = (
            MetricsDumper(self.registry, self.events,
                          self.spec.metrics_period_s)
            if self.events is not None
            and self.spec.metrics_period_s > 0 else None)

    def flush(self) -> None:
        """Export the trace, final-dump metrics, close the event
        log.  Safe to call more than once; never raises.  A faulted
        flush (`obs.flush` site) is itself a flight-recorder trigger
        — the one teardown whose loss the recorder must survive."""
        try:
            from ..utils import faults
            try:
                faults.maybe_fault("obs.flush")
            except Exception:  # noqa: BLE001 — flush fault = trigger
                if self.flightrec is not None:
                    self.flightrec.trigger("obs.flush_fault",
                                           tracer=self.tracer)
            if self._dumper is not None:
                self._dumper.stop(final_dump=True)
                self._dumper = None
            if self.spec.trace:
                self.tracer.export(self.spec.trace)
            if self.events is not None:
                self.events.emit(
                    "obs.flush",
                    spans=len(self.tracer.events()),
                    spans_dropped=self.tracer.dropped,
                    spans_evicted=self.tracer.evicted,
                    spans_sampled_out=self.tracer.sampled_out,
                    events_written=self.events.written,
                    events_dropped=self.events.dropped,
                    events_rotations=self.events.rotations)
                self.events.close()
        except Exception:  # noqa: BLE001 — teardown never raises
            pass


_LOCK = threading.Lock()
_ACTIVE: Optional[Observability] = None


def enable(spec: Optional[ObsSpec] = None) -> Observability:
    """Install a process-global session (replacing — and flushing —
    any previous one).  Returns it."""
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, Observability(spec)
    if prev is not None:
        prev.flush()
    return _ACTIVE


def disable() -> None:
    """Flush and remove the active session.  No-op when off."""
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, None
    if prev is not None:
        prev.flush()


def active() -> Optional[Observability]:
    return _ACTIVE


class session:
    """`with obs.session(spec): ...` — enable for the body, flush on
    exit (tests and bench legs)."""

    def __init__(self, spec: Optional[ObsSpec] = None):
        self._spec = spec

    def __enter__(self) -> Observability:
        return enable(self._spec)

    def __exit__(self, *exc) -> bool:
        disable()
        return False


# -- the instrumented-site API (hot-path: one global read when off) ---------

def span(name: str, corr: Optional[str] = None,
         trace: Optional[str] = None, parent: Optional[int] = None,
         **attrs):
    """Open a trace span, or the shared null span when off.
    `trace`/`parent` anchor under a remote or cross-thread parent
    (the receive side of an `X-Trace-Id`/`X-Parent-Span` hop)."""
    o = _ACTIVE
    if o is None:
        return NULL_SPAN
    return o.tracer.span(name, corr=corr, trace=trace, parent=parent,
                         **attrs)


def current_corr() -> Optional[str]:
    """Correlation id of the innermost open span on this thread (for
    explicit cross-thread hand-off), or None."""
    o = _ACTIVE
    if o is None:
        return None
    return o.tracer.current_corr()


def trace_context() -> Optional[Tuple[str, int]]:
    """`(trace_id, span_id)` of the innermost open span on this
    thread — the value a sender serializes into the
    `X-Trace-Id`/`X-Parent-Span` pair — or None when off / no span."""
    o = _ACTIVE
    if o is None:
        return None
    return o.tracer.context()


def trace_dump() -> Dict[str, Any]:
    """The active tracer's Chrome-trace dict (the `GET /trace` body);
    an empty trace when no session is live."""
    o = _ACTIVE
    if o is None:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    return o.tracer.trace_dict()


def emit_event(kind: str, **fields) -> None:
    """Append a structured event to the active session's JSONL log
    and the flight recorder's ring.  No-op when off; any failure is
    swallowed into the respective drop counter."""
    o = _ACTIVE
    if o is None:
        return
    if o.events is not None:
        o.events.emit(kind, **fields)
    if o.flightrec is not None:
        o.flightrec.observe(kind, fields, tracer=o.tracer)


def sample_trace(trace_id: Optional[str], latency_s: float,
                 p95_s: Optional[float] = None, failed: bool = False,
                 shed: bool = False, hedged: bool = False,
                 resumed: bool = False) -> bool:
    """Apply the session's tail-sampling policy to one finished
    request: returns True when its trace is kept, else discards the
    buffered spans (counted, never raised).  No-op (kept) when off."""
    o = _ACTIVE
    if o is None:
        return True
    keep = o.sampler.keep(latency_s, p95_s=p95_s, failed=failed,
                          shed=shed, hedged=hedged, resumed=resumed)
    if not keep and trace_id:
        o.tracer.discard_trace(trace_id)
    return keep


def registry() -> Optional[MetricsRegistry]:
    """The active session's metrics registry, or None when off."""
    o = _ACTIVE
    return o.registry if o is not None else None


def get_logger(component: str,
               sink: Optional[Callable[..., None]] = None) -> Logger:
    """A component logger usable anywhere a bare `log_fn` is —
    resolves the active event log per call, so it mirrors warning+
    records whenever a session is live."""
    return Logger(component, sink=sink,
                  event_log_for=lambda: (
                      _ACTIVE.events if _ACTIVE is not None else None))
